"""Control-lane transport + coordinator succession (docs/fleet.md).

The fleet's control plane — join/heartbeat/ack/leave and the
coordinator's assignment decisions — historically travelled as direct
method calls into one `FleetCoordinator` object: a perfect, unlosable
bus and a single fatal point. This module replaces both assumptions:

* :class:`ControlBus` turns every control interaction into a RECORD on a
  compacted control topic riding any existing ``Consumer``/``Producer``
  pair (stream/broker.py protocols). With no transport it degrades to an
  in-memory wire — and because the seam is the stream protocols, the
  PR 1 chaos vocabulary (``ChaosConsumer``/``ChaosProducer``/
  ``FaultPlan``: loss, delay, duplication, reorder) applies to the
  CONTROL lane exactly as it does to the data lane. Records carry a
  per-sender sequence (dedup + loss accounting), the publishing
  coordinator's term (stale-term fencing), and a bus-global lamport
  stamp (replay order + snapshot watermarks).

* :class:`SuccessionCoordinator` makes the coordinator itself a LEASED
  ROLE: N candidates contend on it with monotonic terms
  (:class:`TermGate` is the election fence). The incumbent publishes a
  beacon + a state snapshot every tick; when beacons go stale past
  ``role_ttl`` (crash) or an abdication record lands (graceful), a
  standby candidate advances the term, replays the compacted topic
  (newest unfenced snapshot + every worker op past its watermark), and
  installs a reconstructed `FleetCoordinator`. Critically the snapshot
  carries the revoke-barrier holds (``_pending``) and the successor
  re-applies possibly-lost ops from a local outbox, so a mid-rebalance
  failover can neither double-grant a draining owner's partitions nor
  let a zombie commit — the exact choreography `flightcheck model`
  verifies first (analysis/checker.py succession environment; mutations
  ``drop_coordinator_lease``, ``stale_term_fence_accepted``,
  ``forget_holds_on_failover`` each yield a counterexample).

During an interregnum the proxy answers workers from its lease cache
(no mutations: the dead leader's last word stands until a successor
owns the state) and commit fences answer from granted ∪ held pairs —
permissive for a draining old owner, while withheld targets stay
fenced, so both sides of an in-flight handoff keep their invariants.
Worker ops that arrive leaderless still land on the bus (and in the
outbox), which is the whole point: records outlive the brain.

Kill injection (:class:`~fraud_detection_tpu.stream.faults.CoordinatorKillSpec`)
and the `coordinator_kill` game day (scenarios/gameday.py) drive this
live; docs/fleet.md "Coordinator succession" walks a failover trace.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from fraud_detection_tpu.fleet.coordinator import FleetCoordinator, Lease
from fraud_detection_tpu.stream.faults import CoordinatorKilled

#: worker-originated ops replayed into a successor (all idempotent:
#: join/sync renew, ack releases what is already released, leave of a
#: gone member is a no-op — at-least-once redelivery is safe).
WORKER_OPS = ("join", "sync", "ack", "leave")

#: candidate-originated records (never replayed into assignment state).
CANDIDATE_KINDS = ("beacon", "claim", "abdicate")

CONTROL_KINDS = WORKER_OPS + CANDIDATE_KINDS + ("snapshot",)

_COMPACT_AT = 4096      # in-memory log bound before compaction
_OUTBOX_KEEP = 1024     # uncovered-op retry buffer bound
_JOURNAL_KEEP = 16384   # conformance journal bound (never compacted)


@dataclass(frozen=True)
class ControlRecord:
    """One control-lane record. ``seq`` is per-sender and 1-based (the
    dedup/loss key); ``lamport`` is the bus-global publish order (the
    replay key); ``term`` is the publisher's coordinator term at publish
    time (0 for worker ops — workers don't vote, they report)."""

    kind: str
    sender: str
    seq: int
    term: int
    lamport: int
    payload: dict

    def key(self) -> str:
        """Compaction key: last record per (kind, sender) is the one a
        compacted topic retains."""
        return f"{self.kind}:{self.sender}"

    def as_dict(self) -> dict:
        return {"kind": self.kind, "sender": self.sender, "seq": self.seq,
                "term": self.term, "lamport": self.lamport,
                "payload": self.payload}

    @staticmethod
    def from_dict(obj: dict) -> Optional["ControlRecord"]:
        try:
            return ControlRecord(
                str(obj["kind"]), str(obj["sender"]), int(obj["seq"]),
                int(obj["term"]), int(obj["lamport"]),
                dict(obj.get("payload") or {}))
        except (KeyError, TypeError, ValueError):
            return None


class ControlBus:
    """The control lane: publish/poll/replay over a Consumer/Producer
    pair, or an in-memory wire when none is given.

    Thread-safe. Delivery accounting rides per-sender sequences: a seq
    seen twice is a duplicate (dropped — every op is idempotent anyway,
    this just keeps the counters honest), a seq below the sender's high
    watermark is a reorder (accepted; replay sorts by lamport), and gaps
    below the watermark are the lossy lane's casualties (``lost``)."""

    def __init__(self, producer=None, consumer=None, *,
                 topic: str = "__fleet_control"):
        if (producer is None) != (consumer is None):
            raise ValueError("ControlBus needs both a producer and a "
                             "consumer, or neither (in-memory wire)")
        self.topic = topic
        self._producer = producer
        self._consumer = consumer
        self._lock = threading.Lock()
        self._lamport = 0
        self._next_seq: Dict[str, int] = {}     # sender -> last assigned
        self._wire: List[ControlRecord] = []    # in-memory transport
        self._log: List[ControlRecord] = []     # accepted, compacted
        # Conformance journal: every accepted record in delivery order,
        # NEVER compacted (compaction keeps what a successor needs; the
        # journal keeps what an auditor needs — `flightcheck conform`
        # replays it against the FLEET_PROTOCOLS role machines). Bounded;
        # overflow drops the oldest and counts, so a long-lived fleet
        # degrades to a suffix audit instead of unbounded memory.
        self._journal: List[ControlRecord] = []
        self.journal_dropped = 0
        self._seen: Dict[str, Set[int]] = {}    # sender -> delivered seqs
        self._high: Dict[str, int] = {}         # sender -> highest delivered
        self.published = 0
        self.delivered = 0
        self.duplicates_dropped = 0
        self.reordered = 0
        self.stale_snapshots_rejected = 0
        self.compactions = 0

    # ------------------------------------------------------------------
    # publish side (workers + the incumbent coordinator)
    # ------------------------------------------------------------------

    def publish(self, kind: str, sender: str, payload: Optional[dict] = None,
                *, term: int = 0) -> ControlRecord:
        """Stamp and send one record. Transport failures are swallowed —
        a lossy control lane is the operating assumption, not an error;
        the returned record still carries its stamps so callers can
        retry it later (the succession outbox does exactly that)."""
        with self._lock:
            self._lamport += 1
            seq = self._next_seq.get(sender, 0) + 1
            self._next_seq[sender] = seq
            rec = ControlRecord(kind, sender, seq, term, self._lamport,
                                dict(payload or {}))
            self.published += 1
            if self._producer is None:
                self._wire.append(rec)
                return rec
        # Transport outside the bus lock: the producer has its own locks
        # (and chaos wrappers), and the lock graph must stay acyclic.
        try:
            self._producer.produce(
                self.topic, json.dumps(rec.as_dict()).encode("utf-8"),
                key=rec.key().encode("utf-8"))
            self._producer.flush()
        except Exception:  # noqa: BLE001 — chaos loss: the record is gone
            pass
        return rec

    def retry(self, rec: ControlRecord) -> None:
        """Re-send an already-stamped record verbatim (at-least-once: the
        per-sender seq dedups the copy on delivery)."""
        with self._lock:
            if self._producer is None:
                self._wire.append(rec)
                return
        try:
            self._producer.produce(
                self.topic, json.dumps(rec.as_dict()).encode("utf-8"),
                key=rec.key().encode("utf-8"))
            self._producer.flush()
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------------
    # delivery side (candidates / the incumbent)
    # ------------------------------------------------------------------

    def poll(self) -> List[ControlRecord]:
        """Drain the transport into the local log; returns the NEWLY
        accepted records (duplicates dropped, reorders accepted)."""
        raws: List[ControlRecord] = []
        if self._consumer is not None:
            while True:
                msg = self._consumer.poll(0.0)
                if msg is None:
                    break
                rec = self._decode(msg)
                if rec is not None:
                    raws.append(rec)
        with self._lock:
            if self._consumer is None:
                raws = self._wire
                self._wire = []
            accepted: List[ControlRecord] = []
            for rec in raws:
                seen = self._seen.setdefault(rec.sender, set())
                if rec.seq in seen:
                    self.duplicates_dropped += 1
                    continue
                high = self._high.get(rec.sender, 0)
                if rec.seq < high:
                    self.reordered += 1
                seen.add(rec.seq)
                self._high[rec.sender] = max(high, rec.seq)
                self._lamport = max(self._lamport, rec.lamport)
                self.delivered += 1
                accepted.append(rec)
                self._log.append(rec)
                self._journal.append(rec)
            if len(self._journal) > _JOURNAL_KEEP:
                drop = len(self._journal) - _JOURNAL_KEEP
                del self._journal[:drop]
                self.journal_dropped += drop
            if len(self._log) > _COMPACT_AT:
                self._compact_locked()
            return accepted

    @staticmethod
    def _decode(msg) -> Optional[ControlRecord]:
        value = getattr(msg, "value", None)
        if value is None:
            return None
        try:
            obj = json.loads(value.decode("utf-8")
                             if isinstance(value, (bytes, bytearray))
                             else value)
        except (ValueError, AttributeError):
            return None
        return ControlRecord.from_dict(obj) if isinstance(obj, dict) else None

    def replay(self) -> Tuple[Optional[ControlRecord], List[ControlRecord]]:
        """The successor's read: (newest unfenced snapshot, worker ops
        past its watermark in lamport order). Snapshot choice orders by
        (term, lamport) — a stale-term snapshot published LATE (the
        zombie-coordinator dying breath) loses to any newer-term one no
        matter its lamport, and is counted, not honored."""
        with self._lock:
            snaps = [r for r in self._log if r.kind == "snapshot"]
            best: Optional[ControlRecord] = None
            for r in snaps:
                if best is None or (r.term, r.lamport) > (best.term,
                                                          best.lamport):
                    best = r
            if best is not None:
                self.stale_snapshots_rejected += sum(
                    1 for r in snaps
                    if r.term < best.term and r.lamport > best.lamport)
            watermark = (int(best.payload.get("watermark") or 0)
                         if best is not None else 0)
            ops = sorted(
                (r for r in self._log
                 if r.kind in WORKER_OPS and r.lamport > watermark),
                key=lambda r: (r.lamport, r.sender, r.seq))
            return best, ops

    def lamport(self) -> int:
        with self._lock:
            return self._lamport

    def export_trace(self) -> List[dict]:
        """The conformance journal as JSON-ready dicts, delivery order.

        This is the `flightcheck conform` seam: game days persist it in
        their evidence (``succession.trace``) and the conformance checker
        replays it against the declared role machines
        (analysis/entrypoints.py FLEET_PROTOCOLS)."""
        with self._lock:
            return [r.as_dict() for r in self._journal]

    def lost(self) -> int:
        """Records definitely lost below each sender's delivery high
        watermark (in-flight records above it don't count yet)."""
        with self._lock:
            return self._lost_locked()

    def _lost_locked(self) -> int:
        return sum(high - len(self._seen.get(sender, ()))
                   for sender, high in self._high.items())

    def stats(self) -> dict:
        with self._lock:
            return {
                "published": self.published,
                "delivered": self.delivered,
                "lost": self._lost_locked(),
                "duplicates_dropped": self.duplicates_dropped,
                "reordered": self.reordered,
                "stale_snapshots_rejected": self.stale_snapshots_rejected,
                "log": len(self._log),
                "compactions": self.compactions,
                "journal": len(self._journal),
                "journal_dropped": self.journal_dropped,
            }

    def _compact_locked(self) -> None:
        """Compacted-topic semantics on the in-memory log: keep the
        winning snapshot + every worker op past its watermark; candidate
        chatter (beacons/claims) and superseded ops drop."""
        snaps = [r for r in self._log if r.kind == "snapshot"]
        best = max(snaps, key=lambda r: (r.term, r.lamport), default=None)
        watermark = (int(best.payload.get("watermark") or 0)
                     if best is not None else 0)
        keep = [r for r in self._log
                if r.kind in WORKER_OPS and r.lamport > watermark]
        if best is not None:
            keep.append(best)
        keep.sort(key=lambda r: r.lamport)
        self._log = keep
        self.compactions += 1


class KafkaControlBus(ControlBus):
    """Control lane over a real compacted Kafka topic — the cross-host
    transport. Import-gated on confluent_kafka (stream/kafka.py): in
    environments without the wheel, construction raises and the caller
    stays on the in-process bus. The topic should be created with
    ``cleanup.policy=compact`` keyed by ``kind:sender`` (exactly what
    :meth:`ControlRecord.key` emits), so the broker's own compaction
    mirrors :meth:`ControlBus._compact_locked`."""

    def __init__(self, config=None, *, topic: str = "__fleet_control"):
        from fraud_detection_tpu.stream import kafka as _kafka

        if not _kafka.kafka_available():
            raise RuntimeError(
                "KafkaControlBus requires confluent_kafka; use the "
                "in-process ControlBus (or broker consumer/producer "
                "pair) instead")
        producer = _kafka.KafkaProducer(config)
        consumer = _kafka.KafkaConsumer([topic], config)
        super().__init__(producer, consumer, topic=topic)


class TermGate:
    """The election fence: a monotonic term with compare-and-swap
    advance. ``try_advance`` is how a candidate wins (strictly greater
    terms only — two candidates racing the same term elect once);
    ``accept`` is how everyone else decides whether a decision stamped
    with some term is still authoritative."""

    def __init__(self, term: int = 0):
        self._lock = threading.Lock()
        self._term = term

    def current(self) -> int:
        with self._lock:
            return self._term

    def try_advance(self, term: int) -> bool:
        with self._lock:
            if term > self._term:
                self._term = term
                return True
            return False

    def accept(self, term: int) -> bool:
        """A decision stamped ``term`` is acceptable iff no newer term
        has been granted (the stale-term fence: `flightcheck model`
        mutation ``stale_term_fence_accepted`` shows what accepting an
        old term costs — duplicated rows under two coordinators)."""
        with self._lock:
            return term >= self._term


class SuccessionCoordinator:
    """Coordinator-as-a-leased-role: a drop-in `FleetCoordinator`
    surface (join/sync/ack/leave/fence_lost/tick/...) whose actual
    brain is whichever candidate currently holds the role lease.

    See the module docstring for the protocol; thread model: worker
    threads call the membership surface, the fleet monitor calls
    ``tick``, and one thread per candidate calls ``step`` — everything
    shared sits under ``_lock``, elections serialize under
    ``_elect_lock``, and neither is ever held across a call into the
    bus, the gate, or the inner coordinator."""

    def __init__(self, topics: Sequence[str], num_partitions: int, *,
                 bus=None, control: Optional[ControlBus] = None,
                 lease_ttl: float = 30.0,
                 lag_fn: Optional[Callable[[], Optional[int]]] = None,
                 candidates: int = 2, role_ttl: Optional[float] = None,
                 kill=None, clock=time.monotonic, wall=time.time):
        if candidates < 1:
            raise ValueError(f"candidates must be >= 1, got {candidates}")
        self.topics = tuple(topics)
        self.num_partitions = num_partitions
        self.lease_ttl = lease_ttl
        self.role_ttl = role_ttl if role_ttl is not None else lease_ttl / 2
        if self.role_ttl <= 0:
            raise ValueError(f"role_ttl must be > 0, got {self.role_ttl}")
        self._fleet_bus = bus
        self._lag_fn = lag_fn
        self._clock = clock
        self._wall = wall
        self.control = control if control is not None else ControlBus()
        self.gate = TermGate()
        self.kill = kill
        self.candidate_ids = tuple(f"c{i}" for i in range(candidates))
        self._lock = threading.Lock()
        self._elect_lock = threading.Lock()
        self._cands: Dict[str, str] = {c: "standby" for c in
                                       self.candidate_ids}
        self.handoff_log: List[dict] = []
        self.elections = 0
        self._leases: Dict[str, Lease] = {}      # last lease each worker saw
        self._granted: Dict[str, Set[tuple]] = {}
        self._held: Dict[str, Set[tuple]] = {}   # revoked, not yet acked
        self._outbox: List[ControlRecord] = []   # ops possibly lost on wire
        self._counters = {"rebalances": 0, "expirations": 0}
        self._last_view: Optional[dict] = None
        self._abdicated = False
        self._leader_down_at: Optional[float] = None
        self._last_leader: Optional[str] = None
        # Autoscale stats hook (fleet/autoscale/): propagated onto every
        # installed incumbent so the view's ``autoscale`` block survives
        # failover (the property setter below re-wires the live one).
        self._autoscale_stats: Optional[Callable[[], dict]] = None
        # Bootstrap: the first candidate takes term 1 with a fresh
        # coordinator — no interregnum before the fleet's first tick.
        first = self.candidate_ids[0]
        self.gate.try_advance(1)
        coordinator = self._new_coordinator()
        coordinator.term = 1
        coordinator.leader_id = first
        coordinator.control_stats = self.control.stats
        self.coordinator: Optional[FleetCoordinator] = coordinator
        self.leader_id: Optional[str] = first
        self._leader_term = 1
        self._cands[first] = "leading"
        self._last_beacon = self._clock()

    def _new_coordinator(self) -> FleetCoordinator:
        coordinator = FleetCoordinator(
            self.topics, self.num_partitions, bus=self._fleet_bus,
            lease_ttl=self.lease_ttl, lag_fn=self._lag_fn,
            clock=self._clock, wall=self._wall)
        coordinator.autoscale_stats = self._autoscale_stats
        return coordinator

    @property
    def autoscale_stats(self) -> Optional[Callable[[], dict]]:
        return self._autoscale_stats

    @autoscale_stats.setter
    def autoscale_stats(self, fn: Optional[Callable[[], dict]]) -> None:
        with self._lock:
            self._autoscale_stats = fn
            coordinator = self.coordinator
        if coordinator is not None:
            coordinator.autoscale_stats = fn

    # ------------------------------------------------------------------
    # worker-facing surface (worker threads)
    # ------------------------------------------------------------------

    def join(self, worker_id: str) -> Lease:
        with self._lock:
            coordinator = self.coordinator
        if coordinator is None:
            # Interregnum: the op still lands on the bus (records outlive
            # the brain — the successor replays it); the answer is the
            # dead leader's last word, unmutated.
            self._publish_op("join", worker_id)
            return self._cached_lease(worker_id)
        lease = coordinator.join(worker_id)
        self._publish_op("join", worker_id)
        self._cache_lease(worker_id, lease)
        return lease

    def sync(self, worker_id: str) -> Lease:
        with self._lock:
            coordinator = self.coordinator
        if coordinator is None:
            self._publish_op("sync", worker_id)
            return self._cached_lease(worker_id)
        lease = coordinator.sync(worker_id)
        self._publish_op("sync", worker_id)
        self._cache_lease(worker_id, lease)
        return lease

    def ack(self, worker_id: str) -> Lease:
        with self._lock:
            coordinator = self.coordinator
        if coordinator is None:
            self._publish_op("ack", worker_id)
            with self._lock:
                # The worker drained + committed: its holds are over even
                # while leaderless (the replayed ack tells the successor).
                self._held.pop(worker_id, None)
            return self._cached_lease(worker_id)
        lease = coordinator.ack(worker_id)
        self._publish_op("ack", worker_id)
        self._cache_lease(worker_id, lease)
        with self._lock:
            self._held.pop(worker_id, None)
        return lease

    def leave(self, worker_id: str) -> None:
        with self._lock:
            coordinator = self.coordinator
        if coordinator is not None:
            coordinator.leave(worker_id)
        self._publish_op("leave", worker_id)
        with self._lock:
            self._leases.pop(worker_id, None)
            self._granted.pop(worker_id, None)
            self._held.pop(worker_id, None)

    def fence_lost(self, worker_id: str,
                   pairs: Sequence[tuple]) -> List[tuple]:
        """Commit fence. Leaderless, the proxy answers from its lease
        cache: granted ∪ held — a draining old owner's commits stay
        authoritative mid-failover (the revoke barrier holds them until
        its ack), while a pair merely targeted-but-withheld fences. The
        narrow residue (a pre-kill zombie whose expiry the dead leader
        never processed) is exactly what the model's term fences cover —
        the successor's first tick expires it before any re-grant."""
        with self._lock:
            coordinator = self.coordinator
            if coordinator is None:
                own = (self._granted.get(worker_id, set())
                       | self._held.get(worker_id, set()))
                return [p for p in pairs if tuple(p) not in own]
        return coordinator.fence_lost(worker_id, pairs)

    def request_release(self, worker_id: str) -> bool:
        """Coordinator-requested voluntary leave (fleet/autoscale/
        scale-in). Leaderless, the request is REFUSED — the autoscaler
        simply retries next tick; granting from the lease cache could
        shrink a fleet whose successor's replayed state still needs the
        member. A granted release lands in ``export_state`` and rides
        the next snapshot, so an in-flight drain survives failover."""
        with self._lock:
            coordinator = self.coordinator
        if coordinator is None:
            return False
        return coordinator.request_release(worker_id)

    # ------------------------------------------------------------------
    # lease cache + op outbox internals
    # ------------------------------------------------------------------

    def _cache_lease(self, worker_id: str, lease: Lease) -> None:
        granted = {tuple(p) for p in lease.partitions}
        with self._lock:
            old = self._granted.get(worker_id, set())
            revoked = old - granted
            if revoked:
                # Revoked-not-yet-acked: the worker keeps commit rights
                # on these until its drain ack (mirrors _pending).
                self._held.setdefault(worker_id, set()).update(revoked)
            self._granted[worker_id] = granted
            self._leases[worker_id] = lease

    def _cached_lease(self, worker_id: str) -> Lease:
        with self._lock:
            lease = self._leases.get(worker_id)
            if lease is None:
                lease = Lease(worker_id, 0, (), ())
                self._leases[worker_id] = lease
            return lease

    def _publish_op(self, kind: str, worker_id: str) -> None:
        # Apply-then-publish (callers apply first): the record's lamport
        # is assigned AFTER the op landed in coordinator state, so any
        # snapshot watermark covering this lamport covers the op — safe
        # to prune from the outbox.
        rec = self.control.publish(kind, worker_id, {},
                                   term=self.gate.current())
        with self._lock:
            self._outbox.append(rec)
            if len(self._outbox) > _OUTBOX_KEEP:
                del self._outbox[:len(self._outbox) - _OUTBOX_KEEP]

    def _prune_outbox(self, watermark: int) -> None:
        with self._lock:
            self._outbox = [r for r in self._outbox
                            if r.lamport > watermark]

    # ------------------------------------------------------------------
    # the incumbent's tick (fleet monitor thread)
    # ------------------------------------------------------------------

    def tick(self) -> dict:
        # Drain the wire every tick: delivery accounting (lost/reordered)
        # and the conformance journal must not wait for an election's
        # poll — an incumbent that never dies still records an auditable
        # run (`flightcheck conform`).
        self.control.poll()
        with self._lock:
            coordinator = self.coordinator
            leader = self.leader_id
            my_term = self._leader_term
        if coordinator is None or leader is None:
            # Interregnum: give standby candidates a chance (fallback for
            # deployments that never started candidate threads), then
            # answer with the STALE view — its frozen ticks counter is
            # what trips the sentinel's coordinator_absence rule.
            self._maybe_elect()
            with self._lock:
                coordinator = self.coordinator
                if coordinator is None:
                    return dict(self._last_view or {})
                leader = self.leader_id
                my_term = self._leader_term
        kill = self.kill
        if kill is not None:
            try:
                kill.tick(leader)
            except CoordinatorKilled as exc:
                self._on_killed(exc)
                with self._lock:
                    return dict(self._last_view or {})
        if not self.gate.accept(my_term):
            # Zombie incumbent: a newer term won the role while this
            # tick was in flight. Demote WITHOUT publishing — a stale-
            # term snapshot or beacon must never follow a newer fence
            # (FC503 zombie-demotes-before-publish).
            with self._lock:
                if self.coordinator is coordinator:
                    self.coordinator = None
                    self.leader_id = None
                    self._cands[leader] = "standby"
                return dict(self._last_view or {})
        view = coordinator.tick()
        self.control.publish("beacon", leader,
                             {"ticks": view.get("coordinator", {})
                              .get("ticks")}, term=my_term)
        state = coordinator.export_state()
        watermark = self.control.lamport()
        self.control.publish("snapshot", leader,
                             {"state": state, "watermark": watermark},
                             term=my_term)
        self._prune_outbox(watermark)
        with self._lock:
            self._last_beacon = self._clock()
            self._last_view = view
            self._counters["rebalances"] = coordinator.rebalances
            self._counters["expirations"] = coordinator.expirations
        return view

    def _on_killed(self, exc: CoordinatorKilled) -> None:
        with self._lock:
            coordinator = self.coordinator
            cid = self.leader_id
            term = self._leader_term
        if coordinator is None or cid is None:
            return
        if exc.mode == "graceful":
            # Dying breath: a final snapshot + abdication record, so the
            # successor starts from a complete log and elects at once.
            state = coordinator.export_state()
            watermark = self.control.lamport()
            self.control.publish("snapshot", cid,
                                 {"state": state, "watermark": watermark},
                                 term=term)
            self.control.publish("abdicate", cid, {}, term=term)
        with self._lock:
            self._cands[cid] = "dead"
            self._last_leader = cid
            self.coordinator = None
            self.leader_id = None
            self._abdicated = exc.mode == "graceful"
            self._leader_down_at = self._clock()

    # ------------------------------------------------------------------
    # candidate side (one thread per candidate, or inline fallback)
    # ------------------------------------------------------------------

    def step(self, cid: str) -> bool:
        """One candidate pass: contend for the role if it is vacant —
        either announced (abdication) or deduced (no beacon for
        ``role_ttl``, the crash-detection delay a real deployment pays).
        Returns True when this call installed a new incumbent."""
        with self._lock:
            if self._cands.get(cid) != "standby":
                return False
            if not self._vacancy_locked():
                return False
        return self._elect(cid)

    def _vacancy_locked(self) -> bool:
        if self.coordinator is not None:
            return False
        if self._abdicated:
            return True
        return (self._clock() - self._last_beacon) > self.role_ttl

    def _maybe_elect(self) -> None:
        with self._lock:
            if not self._vacancy_locked():
                return
            ready = [c for c, s in self._cands.items() if s == "standby"]
        if ready:
            self._elect(ready[0])

    def _elect(self, cid: str) -> bool:
        with self._elect_lock:
            # Re-check under the election lock: a racing candidate may
            # have just installed itself — without this, the loser would
            # escalate the term and steal a freshly-won role.
            with self._lock:
                if (self._cands.get(cid) != "standby"
                        or not self._vacancy_locked()):
                    return False
                down = self._leader_down_at
            term = self.gate.current() + 1
            if not self.gate.try_advance(term):
                return False
            self.control.publish("claim", cid, {}, term=term)
            self.control.poll()
            snapshot, ops = self.control.replay()
            coordinator = self._reconstruct(snapshot, ops)
            self._install(cid, term, coordinator, down)
            return True

    def _reconstruct(self, snapshot: Optional[ControlRecord],
                     ops: List[ControlRecord]) -> FleetCoordinator:
        """Successor state: newest unfenced snapshot (restoring target,
        REVOKE-BARRIER HOLDS, generation, counters) + every worker op
        past its watermark in lamport order + any outbox op the wire may
        have eaten (at-least-once; ops are idempotent)."""
        coordinator = self._new_coordinator()
        if snapshot is not None:
            coordinator.restore_state(snapshot.payload.get("state") or {})
        watermark = (int(snapshot.payload.get("watermark") or 0)
                     if snapshot is not None else 0)
        with self._lock:
            extra = list(self._outbox)
        delivered = {(r.sender, r.seq) for r in ops}
        replay = list(ops)
        for rec in extra:
            if (rec.kind in WORKER_OPS and rec.lamport > watermark
                    and (rec.sender, rec.seq) not in delivered):
                replay.append(rec)
                self.control.retry(rec)
        replay.sort(key=lambda r: (r.lamport, r.sender, r.seq))
        for rec in replay:
            if rec.kind in ("join", "sync"):
                coordinator.join(rec.sender)
            elif rec.kind == "ack":
                coordinator.ack(rec.sender)
            elif rec.kind == "leave":
                coordinator.leave(rec.sender)
        return coordinator

    def _install(self, cid: str, term: int,
                 coordinator: FleetCoordinator, down: Optional[float]) -> None:
        now = self._clock()
        with self._lock:
            mode = "graceful" if self._abdicated else "crash"
            self.elections += 1
            self.handoff_log.append({
                "term": term,
                "from": self._last_leader,
                "to": cid,
                "mode": mode,
                "failover_s": (round(now - down, 6)
                               if down is not None else 0.0),
                "at": self._wall(),
            })
            coordinator.term = term
            coordinator.leader_id = cid
            coordinator.handoffs = len(self.handoff_log)
            coordinator.elections = self.elections
            coordinator.control_stats = self.control.stats
            self.coordinator = coordinator
            self.leader_id = cid
            self._leader_term = term
            self._cands[cid] = "leading"
            self._abdicated = False
            self._leader_down_at = None
            self._last_beacon = now

    # ------------------------------------------------------------------
    # observability surface (drop-in FleetCoordinator compatibility)
    # ------------------------------------------------------------------

    def assignments(self) -> Dict[str, List[tuple]]:
        with self._lock:
            coordinator = self.coordinator
            if coordinator is None:
                return {w: sorted(g) for w, g in self._granted.items()}
        return coordinator.assignments()

    def committed_lag(self) -> Optional[int]:
        with self._lock:
            coordinator = self.coordinator
        if coordinator is None:
            fn = self._lag_fn
            if fn is None:
                return None
            try:
                return fn()
            except Exception:  # noqa: BLE001 — observability never kills
                return None
        return coordinator.committed_lag()

    def last_view(self) -> Optional[dict]:
        with self._lock:
            coordinator = self.coordinator
            if coordinator is None:
                return self._last_view
        view = coordinator.last_view()
        if view is not None:
            return view
        with self._lock:
            return self._last_view

    @property
    def rebalances(self) -> int:
        with self._lock:
            coordinator = self.coordinator
            if coordinator is None:
                return self._counters["rebalances"]
        return coordinator.rebalances

    @property
    def expirations(self) -> int:
        with self._lock:
            coordinator = self.coordinator
            if coordinator is None:
                return self._counters["expirations"]
        return coordinator.expirations

    @property
    def term(self) -> int:
        return self.gate.current()

    @property
    def handoffs(self) -> int:
        with self._lock:
            return len(self.handoff_log)

    def succession_report(self) -> dict:
        """Evidence block for game days / Fleet.run output."""
        with self._lock:
            leader = self.leader_id
            cands = dict(self._cands)
            elections = self.elections
            handoffs = [dict(h) for h in self.handoff_log]
        return {
            "term": self.gate.current(),
            "leader": leader,
            "candidates": cands,
            "elections": elections,
            "handoffs": handoffs,
            "control": self.control.stats(),
            # The full conformance journal — `flightcheck conform` replays
            # this against the FLEET_PROTOCOLS role machines.
            "trace": self.control.export_trace(),
        }
