"""Fleet coordinator: group membership, partition leases, the global view.

The broker's own consumer groups (stream/broker.py) already scale N engines
behind one topic, but their assignor is reactive — a member only discovers a
rebalance when its next poll is fenced. The fleet layer makes ownership a
first-class, *coordinated* object instead:

* **membership** — workers ``join``/``sync`` (heartbeat) /``leave``; a
  worker that stops heartbeating for ``lease_ttl`` seconds is expired and
  its partitions reassigned (the crash path).
* **leases** — every worker owns an EXPLICIT (topic, partition) set,
  granted by the balanced-sticky assignor here and consumed through the
  broker's manual-assignment mode (``InProcessBroker.assigned_consumer``).
* **revoke barrier** — when a rebalance moves a partition away from a LIVE
  worker, the new owner's lease withholds it until the old owner has
  drained its in-flight batches, committed, and ``ack``ed (the
  revoke->drain->commit->reassign choreography, docs/fleet.md). A dead
  worker's partitions skip the barrier: its lease expiry IS the barrier,
  and the group-durable committed offsets are the zero-loss resume point.
* **global backlog watermark** — each tick aggregates the per-worker
  backlogs published on the fleet bus into ONE global number and publishes
  it back (``backlog_per_worker``); every worker's admission controller
  then sheds against the FLEET's queue depth instead of its own partitions'
  (sched/scheduler.py ``fleet_backlog``), so one drowning fleet sheds
  everywhere at once instead of each worker guessing from its own slice.

Thread model: workers call join/sync/ack/leave/fence_lost from their own
threads and the monitor thread calls ``tick`` — every mutation sits under
one lock, and the coordinator never calls back into engines, consumers, or
the broker while holding it (the fleet's lock graph stays acyclic;
flightcheck FC101 checks the composed ordering).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from fraud_detection_tpu.obs.trace import fleet_stage_latency


@dataclass(frozen=True)
class Lease:
    """One worker's partition ownership at one assignment generation."""

    worker_id: str
    generation: int
    partitions: Tuple[tuple, ...]    # granted pairs, sorted
    pending: Tuple[tuple, ...]       # target pairs withheld behind a live
                                     # previous owner's drain barrier
    released: bool = False           # the coordinator requested this
                                     # worker's voluntary leave (scale-in):
                                     # drain + commit + ack, then exit


class FleetCoordinator:
    """Lease-based partition assignment + fleet-view aggregation."""

    def __init__(self, topics: Sequence[str], num_partitions: int, *,
                 bus=None, lease_ttl: float = 30.0,
                 lag_fn: Optional[Callable[[], Optional[int]]] = None,
                 clock=time.monotonic, wall=time.time):
        if num_partitions < 1:
            raise ValueError(
                f"num_partitions must be >= 1, got {num_partitions}")
        if lease_ttl <= 0:
            raise ValueError(f"lease_ttl must be > 0, got {lease_ttl}")
        self.topics = tuple(topics)
        self.num_partitions = num_partitions
        self.bus = bus
        self.lease_ttl = lease_ttl
        # Optional committed-offset lag probe (rows appended but not yet
        # committed by the group, fleet-wide): the drain-run termination
        # signal workers consult when idle — it still counts a dead
        # worker's unreassigned partitions, which per-worker backlogs
        # cannot see (Fleet.in_process wires it to the broker).
        self._lag_fn = lag_fn
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._members: Dict[str, dict] = {}   # wid -> {renewed, joined}
        self._target: Dict[str, Set[tuple]] = {}
        self._pending: Dict[tuple, str] = {}  # pair -> live holder draining it
        # Last GRANTED set actually issued to each worker (join/sync/ack
        # response). A re-deal may only create a NEW barrier hold for a
        # pair its previous owner was really issued — a pair that merely
        # transited a member's target between two of its syncs leaves no
        # read-ahead to drain, and a phantom hold for it would never be
        # acked (the member's lease never changes, so it never drains),
        # withholding the pair from its new owner FOREVER (flightcheck
        # check_liveness lasso, `every_row_eventually_committed`).
        self._issued: Dict[str, Set[tuple]] = {}
        # Members the autoscaler asked to leave (scale-in): excluded from
        # every re-deal but still live barrier HOLDERS until they drain,
        # commit, and ack — release rides the EXISTING revoke barrier.
        self._released: Set[str] = set()
        self._generation = 0
        self._join_seq = 0
        self._all_pairs = [(t, p) for t in self.topics
                           for p in range(num_partitions)]
        self.rebalances = 0
        self.expirations = 0
        self._last_view: Optional[dict] = None
        self._peak_backlog = 0   # max global backlog any tick aggregated
        # Succession identity (fleet/control.py): which term/leader this
        # coordinator instance serves under. A standalone coordinator is
        # its own term-1 incumbent; SuccessionCoordinator._install
        # overwrites these on every failover, and export_state/
        # restore_state carry the assignment state between incumbents.
        self.term = 1
        self.leader_id = "c0"
        self.handoffs = 0
        self.elections = 0
        self._ticks = 0
        self._last_tick_at: Optional[float] = None
        # Optional control-lane stats callable (ControlBus.stats) merged
        # into the view's coordinator block when succession is wired.
        self.control_stats: Optional[Callable[[], dict]] = None
        # Optional autoscale stats callable (Autoscaler.stats) merged
        # into the view as its ``autoscale`` block when elasticity is
        # wired (fleet/autoscale/ — schema pinned by
        # tests AUTOSCALE_BLOCK_SCHEMA, FC301).
        self.autoscale_stats: Optional[Callable[[], dict]] = None

    # ------------------------------------------------------------------
    # membership (worker threads)
    # ------------------------------------------------------------------

    def join(self, worker_id: str) -> Lease:
        with self._lock:
            now = self._clock()
            # Renew the caller FIRST: a syncing member is alive by
            # definition and must never fall to its own expiry scan.
            new = worker_id not in self._members
            if new:
                self._members[worker_id] = {"renewed": now,
                                            "joined": self._join_seq}
                self._join_seq += 1
            else:
                self._members[worker_id]["renewed"] = now
            expired = self._expire_locked(now)
            if new or expired:
                self._rebalance_locked()
            return self._lease_locked(worker_id)

    def sync(self, worker_id: str) -> Lease:
        """Heartbeat + current lease. A worker whose lease expired while it
        wasn't heartbeating transparently rejoins — with a FRESH lease whose
        partitions resume from the group's committed offsets (its old
        read-ahead is gone; the in-between owner was authoritative)."""
        return self.join(worker_id)

    def ack(self, worker_id: str) -> Lease:
        """The worker declares it has stopped consuming everything outside
        its current lease (engine drained, offsets committed, old consumer
        closed) — releases every partition it was holding behind the revoke
        barrier, so the new owners' next ``sync`` grants them."""
        with self._lock:
            released = [pair for pair, holder in self._pending.items()
                        if holder == worker_id]
            for pair in released:
                del self._pending[pair]
            if worker_id in self._members:
                self._members[worker_id]["renewed"] = self._clock()
            return self._lease_locked(worker_id)

    def leave(self, worker_id: str) -> None:
        """Graceful departure (the worker already drained + committed):
        its partitions reassign immediately — no barrier, no ttl wait."""
        with self._lock:
            self._released.discard(worker_id)
            self._issued.pop(worker_id, None)
            if worker_id not in self._members:
                return
            del self._members[worker_id]
            for pair in [p for p, h in self._pending.items()
                         if h == worker_id]:
                del self._pending[pair]
            self._rebalance_locked()

    def request_release(self, worker_id: str) -> bool:
        """Coordinator-requested VOLUNTARY LEAVE (the autoscaler's
        scale-in actuator). The member is excluded from the re-deal NOW —
        its pairs move to the surviving members *behind the existing
        revoke barrier*, so the released worker drains and commits every
        in-flight batch before the new owners may poll (`flightcheck
        model` verifies this composition; mutation ``release_before_drain``
        is the counterexample). The worker observes the released lease on
        its next sync/ack and exits through the graceful-leave path.

        Refused (returns False) for a non-member, a member already
        released, or when granting it would leave the fleet without an
        active (non-released) member."""
        with self._lock:
            if worker_id not in self._members \
                    or worker_id in self._released:
                return False
            active = [w for w in self._members if w not in self._released]
            if len(active) < 2:
                return False
            self._released.add(worker_id)
            self._rebalance_locked()
            return True

    def fence_lost(self, worker_id: str, pairs: Sequence[tuple]) -> List[tuple]:
        """Commit fence for the assigned consumer: which of ``pairs`` does
        ``worker_id`` NOT currently own? Non-empty for a zombie whose lease
        expired (its commit must fail — the new owner is authoritative),
        empty in normal operation. A pair the worker is still draining
        behind the revoke barrier is still the worker's to commit — but a
        pair merely TARGETED at the worker while withheld behind a peer's
        drain is not: until that peer commit-acks, the peer's commits are
        the authoritative ones, and letting the target owner commit too
        lets both sides durably commit the same rows (flightcheck
        model-checker counterexample: a stalled worker rejoins and is
        re-dealt its old pair as target while the in-between owner is
        mid-drain; regression: tests/test_fleet.py
        test_coordinator_fence_blocks_withheld_target)."""
        with self._lock:
            held = {p for p, h in self._pending.items() if h == worker_id}
            granted = {p for p in self._target.get(worker_id, set())
                       if self._pending.get(p) in (None, worker_id)}
            return [p for p in pairs if tuple(p) not in granted
                    and tuple(p) not in held]

    # ------------------------------------------------------------------
    # succession state transfer (fleet/control.py)
    # ------------------------------------------------------------------

    def export_state(self) -> dict:
        """JSON-safe snapshot of the assignment state a successor needs:
        membership (join order preserved), the target map, and — the part
        a naive rebuild-from-targets drops — the revoke-barrier holds
        (``_pending``), so a mid-rebalance failover cannot re-grant a
        pair its draining old owner still commits on (`flightcheck
        model` mutation ``forget_holds_on_failover``)."""
        with self._lock:
            return {
                "term": self.term,
                "generation": self._generation,
                "join_seq": self._join_seq,
                "members": {w: info["joined"]
                            for w, info in self._members.items()},
                "target": {w: sorted([t, p] for (t, p) in pairs)
                           for w, pairs in self._target.items()},
                "pending": sorted(
                    [[t, p], holder]
                    for (t, p), holder in self._pending.items()),
                # In-flight scale-in drains: a successor must keep a
                # released member OUT of its re-deals, or failover would
                # silently cancel the voluntary leave mid-drain.
                "released": sorted(self._released),
                # Issued leases travel too: without them a successor's
                # first re-deal could not tell a pair with real
                # read-ahead behind it from one that merely transited a
                # target — it would either drop a needed hold (barrier
                # breach) or mint a phantom one (livelock).
                "issued": {w: sorted([t, p] for (t, p) in pairs)
                           for w, pairs in self._issued.items()},
                "rebalances": self.rebalances,
                "expirations": self.expirations,
                "ticks": self._ticks,
            }

    def restore_state(self, state: dict) -> None:
        """Adopt an exported snapshot (the successor's first act). Every
        restored member gets a FRESH renewal stamp: the successor cannot
        know how stale each lease was when the old incumbent died, and
        guessing short would expire live workers en masse — a dead
        worker just pays one extra ``lease_ttl`` before its partitions
        move, which the committed offsets make safe."""
        with self._lock:
            now = self._clock()
            members = state.get("members") or {}
            self._members = {w: {"renewed": now, "joined": int(joined)}
                             for w, joined in members.items()}
            self._join_seq = max(
                int(state.get("join_seq") or 0),
                max((int(j) for j in members.values()), default=-1) + 1)
            self._target = {
                w: {(t, p) for t, p in pairs}
                for w, pairs in (state.get("target") or {}).items()}
            self._pending = {
                (t, p): holder
                for (t, p), holder in (state.get("pending") or [])
                if holder in self._members}
            self._released = {w for w in (state.get("released") or [])
                              if w in self._members}
            # Snapshots from before the issued-lease field default to
            # "everything targeted was issued": conservative — it can
            # mint a phantom hold, never drop a real one.
            issued = state.get("issued")
            if issued is None:
                self._issued = {w: set(pairs)
                                for w, pairs in self._target.items()}
            else:
                self._issued = {w: {(t, p) for t, p in pairs}
                                for w, pairs in issued.items()
                                if w in self._members}
            self._generation = int(state.get("generation") or 0)
            self.rebalances = int(state.get("rebalances") or 0)
            self.expirations = int(state.get("expirations") or 0)
            self._ticks = int(state.get("ticks") or 0)

    # ------------------------------------------------------------------
    # assignment internals (caller holds self._lock)
    # ------------------------------------------------------------------

    def _expire_locked(self, now: float) -> bool:
        """Drop members whose lease ran out; returns True when any did
        (the CALLER then rebalances — join/tick fold it into one re-deal)."""
        stale = [w for w, info in self._members.items()
                 if now - info["renewed"] > self.lease_ttl]
        for w in stale:
            del self._members[w]
            self._released.discard(w)
            # A dead incarnation's issued lease must not vouch for its
            # successor: a rejoin starts with nothing issued.
            self._issued.pop(w, None)
            # Expiry IS the drain barrier for a dead worker: release its
            # holds — the committed offsets are the resume point.
            for pair in [p for p, h in self._pending.items() if h == w]:
                del self._pending[pair]
            self.expirations += 1
        return bool(stale)

    def _rebalance_locked(self) -> None:
        """Balanced-sticky re-deal (same shape as the broker's assignor):
        every member keeps what it owns up to its fair share; only orphaned
        pairs and the excess above a shrunken share move. Pairs leaving a
        LIVE member enter the revoke barrier (``_pending``) until that
        member acks its drain."""
        old = {pair: w for w, pairs in self._target.items() for pair in pairs}
        members = sorted(self._members,
                         key=lambda w: self._members[w]["joined"])
        # Released members (scale-in in flight) get NOTHING from the deal
        # — their whole lease is revoked — but stay live barrier holders
        # below until their drain acks.
        deal = [w for w in members if w not in self._released]
        self._generation += 1
        self.rebalances += 1
        self._target = {w: set() for w in members}
        if deal:
            base, extra = divmod(len(self._all_pairs), len(deal))
            share = {w: base + (1 if i < extra else 0)
                     for i, w in enumerate(deal)}
            kept: Dict[str, list] = {w: [] for w in deal}
            pool = []
            for pair in self._all_pairs:      # partition order: deterministic
                w = old.get(pair)
                if w in share and len(kept[w]) < share[w]:
                    kept[w].append(pair)
                else:
                    pool.append(pair)
            for w in deal:                    # join order: deterministic
                take = share[w] - len(kept[w])
                if take > 0:
                    kept[w].extend(pool[:take])
                    del pool[:take]
            for w in deal:
                self._target[w].update(kept[w])
        # Barrier: pairs that moved away from a still-live previous owner
        # wait for its drain ack; everything else (dead/absent owner, or
        # still with its owner) clears immediately. An EXISTING hold outlives
        # re-deals: the holder is whoever actually consumed the pair, and
        # until it acks, re-targeting the pair (a second rebalance before the
        # drain finishes) must not hand it to the next owner — rebuilding
        # from the target map alone dropped exactly those holds (flightcheck
        # model-checker counterexample, mutation `forget_barrier_holds`;
        # regression: tests/test_fleet.py
        # test_coordinator_barrier_survives_consecutive_rebalances).
        # Iterates ALL pairs, not just targeted ones: a pair the deal has
        # nobody to give to yet (every dealable member released mid-scale-
        # in) keeps its live holder's hold — the hold protects the pair's
        # NEXT owner, whoever that turns out to be.
        # A NEW hold (no existing one) additionally requires the previous
        # owner to have been ISSUED the pair: only a granted lease can
        # carry read-ahead worth draining. Without this gate, a pair that
        # bounced through a member's target while it never synced (expired
        # peer's pair parked on it, then re-dealt away) acquires a hold
        # its "holder" can never ack — found as a
        # `every_row_eventually_committed` lasso by flightcheck's
        # liveness checker (regression: tests/test_fleet.py
        # test_coordinator_no_phantom_hold_for_unissued_pair).
        new_owner = {pair: w for w, pairs in self._target.items()
                     for pair in pairs}
        self._pending = {
            pair: holder
            for pair in self._all_pairs
            for holder in (self._pending.get(pair)
                           if pair in self._pending
                           else self._issued_holder_locked(pair, old),)
            if holder is not None and holder != new_owner.get(pair)
            and holder in self._members}

    def _issued_holder_locked(self, pair, old) -> Optional[str]:
        holder = old.get(pair)
        if holder is not None \
                and pair not in self._issued.get(holder, ()):
            return None
        return holder

    def _lease_locked(self, worker_id: str) -> Lease:
        target = self._target.get(worker_id, set())
        withheld = tuple(sorted(
            p for p in target
            if self._pending.get(p) not in (None, worker_id)))
        granted = tuple(sorted(p for p in target if p not in withheld))
        self._issued[worker_id] = set(granted)
        return Lease(worker_id, self._generation, granted, withheld,
                     released=worker_id in self._released)

    # ------------------------------------------------------------------
    # observability + aggregation (monitor thread)
    # ------------------------------------------------------------------

    def assignments(self) -> Dict[str, List[tuple]]:
        with self._lock:
            return {w: sorted(pairs) for w, pairs in self._target.items()}

    def committed_lag(self) -> Optional[int]:
        """Rows not yet committed by the group, fleet-wide (None when no
        probe is wired). Counts dead workers' unreassigned partitions."""
        fn = self._lag_fn
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # noqa: BLE001 — observability must not kill serving
            return None

    def tick(self) -> dict:
        """One coordinator pass: expire dead leases, aggregate the bus into
        the fleet view, publish it back. Returns the view."""
        with self._lock:
            if self._expire_locked(self._clock()):
                self._rebalance_locked()
            self._ticks += 1
            self._last_tick_at = self._clock()
            generation = self._generation
            members = set(self._members)
            assignments = {w: sorted(pairs)
                           for w, pairs in self._target.items()}
            pending = len(self._pending)
            rebalances, expirations = self.rebalances, self.expirations
        snaps = self.bus.snapshots() if self.bus is not None else {}
        backlogs: Dict[str, int] = {}
        shed_total = 0
        processed_total = 0
        stage_wires: List[dict] = []
        alerts_firing = 0
        alerts_critical = 0
        worker_alerts: Dict[str, list] = {}
        for wid, entry in snaps.items():
            if wid not in members:
                continue    # departed/expired worker's stale publish
            doc = entry.get("health") or {}
            b = doc.get("backlog")
            if isinstance(b, (int, float)):
                backlogs[wid] = int(b)
            engine = doc.get("engine") or {}
            shed_total += engine.get("shed") or 0
            processed_total += engine.get("processed") or 0
            obs = doc.get("obs") or {}
            if isinstance(obs.get("stages"), dict):
                stage_wires.append(obs["stages"])
            # Per-worker sentinel states riding the bus (obs/sentinel/):
            # aggregate into the fleet view the coordinator-level
            # worker_alerts rule judges.
            alerts = doc.get("alerts")
            if isinstance(alerts, dict):
                firing = alerts.get("firing") or []
                alerts_firing += len(firing)
                alerts_critical += len(alerts.get("critical_firing") or [])
                if firing:
                    worker_alerts[wid] = list(firing)
        global_backlog = sum(backlogs.values()) if backlogs else None
        if global_backlog is not None:
            self._peak_backlog = max(self._peak_backlog, global_backlog)
        view = {
            "time": self._wall(),
            "generation": generation,
            "workers": sorted(members),
            # Membership COUNT as a first-class metric: the fleet
            # sentinel's worker_absence rule is a window delta over it
            # (a drop means a death or lease expiry — capacity gone).
            "n_workers": len(members),
            # Fleet-wide count of firing worker-level alerts (+ the
            # critical subset) and which worker is firing what.
            "alerts_firing": alerts_firing,
            "alerts_critical": alerts_critical,
            "worker_alerts": worker_alerts,
            "assignments": assignments,
            "pending_release": pending,
            "rebalances": rebalances,
            "expirations": expirations,
            "lease_ttl_sec": self.lease_ttl,
            "global_backlog": global_backlog,
            "peak_global_backlog": self._peak_backlog,
            "backlog_per_worker": (
                round(global_backlog / max(1, len(members)), 1)
                if global_backlog is not None else None),
            "per_worker_backlog": backlogs,
            "shed_total": shed_total,
            "processed_total": processed_total,
            "committed_lag": self.committed_lag(),
            # Fleet-level p50/p99 per pipeline stage: the workers' sketch
            # wires merge LOSSLESSLY (bucket counts add — obs/trace.py),
            # so this equals a single-process run over the same samples.
            # None when no worker is tracing.
            "stage_latency_ms": (fleet_stage_latency(stage_wires)
                                 if stage_wires else None),
            # Who is coordinating, under what term, and how the control
            # lane is faring — the block the sentinel's coordinator
            # rules judge (a frozen ``ticks`` counter IS the absence
            # signal: an interregnum keeps republishing the stale view).
            "coordinator": self._coordinator_block(),
        }
        # Elasticity block (fleet/autoscale/): desired-vs-live capacity,
        # cumulative scale counters the sentinel's autoscale_flap rule
        # windows over, and the last decision with its evidence. Absent
        # (not null) when autoscaling isn't wired, so '+'-joined sentinel
        # paths over the counters abstain instead of reading zeros.
        scale_fn = self.autoscale_stats
        if scale_fn is not None:
            try:
                view["autoscale"] = scale_fn()
            except Exception:  # noqa: BLE001 — observability never kills
                pass
        with self._lock:
            self._last_view = view
        if self.bus is not None:
            self.bus.publish_fleet(view)
        return view

    def _coordinator_block(self) -> dict:
        """The view's ``coordinator`` block (schema pinned by
        tests/test_succession.py COORDINATOR_BLOCK_SCHEMA, FC301):
        succession identity + liveness + control-lane delivery health."""
        with self._lock:
            ticks = self._ticks
            last = self._last_tick_at
        age = round(self._clock() - last, 6) if last is not None else None
        stats_fn = self.control_stats
        control = None
        if stats_fn is not None:
            try:
                control = stats_fn()
            except Exception:  # noqa: BLE001 — observability never kills
                control = None
        return {
            "term": self.term,
            "leader": self.leader_id,
            "handoffs": self.handoffs,
            "elections": self.elections,
            "ticks": ticks,
            "last_tick_age_s": age,
            "control": control,
        }

    def last_view(self) -> Optional[dict]:
        with self._lock:
            return self._last_view
