"""Fleet facade: N partition-owning workers, one coordinator, one bus.

The assembly layer the serve CLI (``--fleet N``) and the bench's ``fleet``
section drive: construct the bus + coordinator, build one
:class:`~fraud_detection_tpu.fleet.worker.FleetWorker` per slot, run them
on threads with a monitor thread ticking the coordinator (lease expiry,
global-backlog aggregation, optional fleet health file), and merge the
results into one stats dict. ``Fleet.in_process`` wires everything against
an :class:`~fraud_detection_tpu.stream.broker.InProcessBroker` — the
manual-assignment consumers, the commit fence, the group-lag drain signal,
per-worker adaptive schedulers with the fleet backlog source — which is
the configuration the tests, the bench, and the demo CLI all share
(docs/fleet.md).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from fraud_detection_tpu.fleet.bus import FleetBus
from fraud_detection_tpu.fleet.coordinator import FleetCoordinator
from fraud_detection_tpu.fleet.worker import FleetWorker
from fraud_detection_tpu.obs.trace import RowTracer
from fraud_detection_tpu.stream.engine import StreamStats, _merge_stats
from fraud_detection_tpu.utils import get_logger
from fraud_detection_tpu.utils.atomicio import atomic_write_json

log = get_logger("fleet")


class Fleet:
    """N fleet workers + coordinator + monitor, run to completion or until
    ``stop()``. Build directly with factories, or via :meth:`in_process`."""

    def __init__(self, n_workers: int, make_engine: Callable,
                 make_consumer: Callable, *,
                 topics, num_partitions: int,
                 bus: Optional[FleetBus] = None,
                 lease_ttl: float = 30.0,
                 lag_fn=None,
                 death_plan=None,
                 heartbeat_interval: float = 0.2,
                 tick_interval: float = 0.2,
                 health_file: Optional[str] = None,
                 trace: bool = False,
                 trace_sample: float = 1.0,
                 trace_seed: Optional[int] = None,
                 sentinel_rules=None,
                 worker_sentinel_rules=None,
                 sentinel_clock=None,
                 sentinel_recorder=None,
                 candidates: int = 1,
                 role_ttl: Optional[float] = None,
                 coordinator_kill=None,
                 control=None,
                 autoscale=None,
                 worker_prefix: str = "w"):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        if tick_interval <= 0:
            raise ValueError(
                f"tick_interval must be > 0, got {tick_interval}")
        self.bus = bus if bus is not None else FleetBus()
        # Coordinator succession (fleet/control.py, docs/fleet.md):
        # ``candidates >= 2`` (or an injected coordinator kill) replaces
        # the single FleetCoordinator with the leased-role proxy —
        # candidate threads contend on the role lease and a successor
        # reconstructs assignment state from the control bus. The plain
        # single-coordinator path is untouched otherwise.
        if candidates > 1 or coordinator_kill is not None:
            from fraud_detection_tpu.fleet.control import \
                SuccessionCoordinator

            if coordinator_kill is not None \
                    and coordinator_kill.kills >= candidates:
                raise ValueError(
                    f"coordinator_kill.kills ({coordinator_kill.kills}) "
                    f"must be < candidates ({candidates}): someone has "
                    f"to survive to coordinate")
            self.coordinator = SuccessionCoordinator(
                topics, num_partitions, bus=self.bus, control=control,
                lease_ttl=lease_ttl, lag_fn=lag_fn,
                candidates=candidates, role_ttl=role_ttl,
                kill=coordinator_kill)
        else:
            self.coordinator = FleetCoordinator(
                topics, num_partitions, bus=self.bus, lease_ttl=lease_ttl,
                lag_fn=lag_fn)
        self.coordinator_kill = coordinator_kill
        # Fleet alerting (obs/sentinel/, docs/observability.md):
        # ``sentinel_rules`` arms a COORDINATOR-level sentinel over the
        # aggregated fleet view (global watermark burn, worker absence,
        # worker-alert roll-up), evaluated once per monitor tick right
        # after the coordinator aggregates; per-worker sentinels (the
        # default engine pack unless ``worker_sentinel_rules`` overrides)
        # watch each worker's own engine health on the poll path and ride
        # the bus, which is what the roll-up aggregates. ``sentinel_clock``
        # injects the stamp domain (the scenario harness passes virtual
        # time); None = process monotonic.
        self.sentinel = None
        self.worker_sentinels: dict = {}
        self._spawn_worker_rules = None     # per-worker pack for scale-outs
        if sentinel_rules is not None:
            from fraud_detection_tpu.obs.sentinel import (Sentinel,
                                                          default_rule_pack)

            kw = {} if sentinel_clock is None else {"clock": sentinel_clock}
            self.sentinel = Sentinel(
                lambda: {"fleet": self.coordinator.last_view() or {}},
                sentinel_rules, worker="fleet",
                recorder=sentinel_recorder, **kw)
            worker_rules = (worker_sentinel_rules
                            if worker_sentinel_rules is not None
                            else default_rule_pack(
                                fast_s=2.0, slow_s=8.0, resolve_s=1.0,
                                p99_ms=60000.0, stall_s=30.0))
            if worker_rules:
                self._spawn_worker_rules = worker_rules
                holder = self.worker_sentinels
                for i in range(n_workers):
                    wid = f"{worker_prefix}{i}"

                    def source(w=wid):
                        worker = self._worker_by_id.get(w)
                        return worker.health() if worker is not None else None

                    holder[wid] = Sentinel(source, worker_rules,
                                           worker=wid, **kw)
        self.death_plan = death_plan
        self.tick_interval = tick_interval
        self.health_file = health_file
        # Saved factory wiring so the autoscaler's provisioner can build
        # workers AFTER construction exactly the way __init__ does.
        self._make_engine = make_engine
        self._make_consumer = self._bind_consumer_factory(make_consumer)
        self.heartbeat_interval = heartbeat_interval
        self.worker_prefix = worker_prefix
        self._trace = trace
        self._trace_sample = trace_sample
        self._trace_seed = trace_seed
        self._sentinel_kw = ({} if sentinel_clock is None
                             else {"clock": sentinel_clock})
        self._idle_timeout: Optional[float] = None
        # Row tracing (docs/observability.md): one RowTracer per worker,
        # shared across that worker's engine incarnations — make_engine
        # factories look it up via ``tracers`` (Fleet.in_process wires it
        # automatically) and the workers publish stage-sketch wires on
        # the bus for the coordinator's fleet-level merge.
        self.tracers = ({f"{worker_prefix}{i}": RowTracer(
                            worker=f"{worker_prefix}{i}",
                            sample=trace_sample, seed=trace_seed)
                         for i in range(n_workers)} if trace else {})
        self.workers: List[FleetWorker] = [
            FleetWorker(f"{worker_prefix}{i}", self.coordinator, self.bus,
                        make_engine,
                        self._make_consumer,
                        death_plan=death_plan,
                        heartbeat_interval=heartbeat_interval,
                        rowtrace=self.tracers.get(f"{worker_prefix}{i}"),
                        sentinel=self.worker_sentinels.get(
                            f"{worker_prefix}{i}"))
            for i in range(n_workers)]
        self._worker_by_id = {w.worker_id: w for w in self.workers}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # Registry lock for DYNAMIC membership (fleet/autoscale/): the
        # monitor thread appends scaled-out workers/threads while run()'s
        # join loop and health snapshots iterate — every reader takes a
        # snapshot under this lock, every writer appends under it.
        self._registry = threading.Lock()
        # Closed-loop elasticity (docs/autoscaling.md): a ScalePolicy (or
        # its kwargs as a dict) arms the Autoscaler on the monitor tick —
        # sentinel signals in, provisioner launches / voluntary-leave
        # releases out, every decision term-stamped on the control lane.
        self.autoscaler = None
        if autoscale is not None:
            from fraud_detection_tpu.fleet.autoscale import (
                Autoscaler, ScalePolicy, ThreadProvisioner)

            policy = (autoscale if isinstance(autoscale, ScalePolicy)
                      else ScalePolicy(**dict(autoscale)))
            sentinel = self.sentinel
            if sentinel is not None:
                # Share the fleet sentinel's clock domain (virtual
                # seconds under the scenario harness) WITHOUT advancing
                # it: decisions are stamped at the evaluation that
                # produced their signals.
                scale_clock = lambda: sentinel.last_eval_at() or 0.0  # noqa: E731
                firing = sentinel.firing
            else:
                # Signal-less elasticity still replaces dead capacity.
                scale_clock = time.monotonic
                firing = None
            self.autoscaler = Autoscaler(
                policy, ThreadProvisioner(self._spawn_worker),
                self.coordinator, initial_workers=n_workers,
                firing=firing,
                # Decisions ride the SAME control lane succession uses
                # (the proxy owns one even when none was injected), so a
                # successor inherits the sizing history.
                control=(control if control is not None
                         else getattr(self.coordinator, "control", None)),
                recorder=sentinel_recorder,
                clock=scale_clock, worker_prefix=worker_prefix)
            self.coordinator.autoscale_stats = self.autoscaler.stats

    @staticmethod
    def _bind_consumer_factory(make_consumer):
        return make_consumer

    def _spawn_worker(self, worker_id: str) -> bool:
        """ThreadProvisioner's spawn hook (fleet/autoscale/): build one
        more FleetWorker exactly the way __init__ does — same factories,
        its own tracer and sentinel — register it, and start its thread.
        Runs on the monitor thread; refuses once shutdown began (a
        scale-out must never outlive ``stop()``)."""
        if self._stop.is_set():
            return False
        with self._registry:
            if worker_id in self._worker_by_id:
                return True     # idempotent retry: already provisioned
            if self._trace:
                self.tracers[worker_id] = RowTracer(
                    worker=worker_id, sample=self._trace_sample,
                    seed=self._trace_seed)
            if self._spawn_worker_rules:
                from fraud_detection_tpu.obs.sentinel import Sentinel

                def source(w=worker_id):
                    worker = self._worker_by_id.get(w)
                    return worker.health() if worker is not None else None

                self.worker_sentinels[worker_id] = Sentinel(
                    source, self._spawn_worker_rules, worker=worker_id,
                    **self._sentinel_kw)
            worker = FleetWorker(
                worker_id, self.coordinator, self.bus, self._make_engine,
                self._make_consumer, death_plan=self.death_plan,
                heartbeat_interval=self.heartbeat_interval,
                rowtrace=self.tracers.get(worker_id),
                sentinel=self.worker_sentinels.get(worker_id))
            self.workers.append(worker)
            self._worker_by_id[worker_id] = worker
            thread = threading.Thread(
                target=self._worker_main, args=(worker, self._idle_timeout),
                name=f"fleet-{worker_id}", daemon=True)
            self._threads.append(thread)
        thread.start()
        log.info("fleet scaled out: %s provisioned", worker_id)
        return True

    # ------------------------------------------------------------------
    # in-process wiring (tests / bench / demo CLI)
    # ------------------------------------------------------------------

    @classmethod
    def in_process(cls, broker, pipeline, input_topic: str,
                   output_topic: str, n_workers: int, *,
                   group_id: str = "fleet",
                   batch_size: int = 1024,
                   max_wait: float = 0.02,
                   pipeline_depth: int = 2,
                   async_dispatch: bool = False,
                   sched_config=None,
                   dlq_topic: Optional[str] = None,
                   death_plan=None,
                   fault_plan=None,
                   bus_dir: Optional[str] = None,
                   lease_ttl: float = 5.0,
                   heartbeat_interval: float = 0.05,
                   tick_interval: float = 0.05,
                   health_file: Optional[str] = None,
                   trace: bool = False,
                   trace_sample: float = 1.0,
                   trace_seed: Optional[int] = None,
                   sentinel_rules=None,
                   worker_sentinel_rules=None,
                   sentinel_clock=None,
                   sentinel_recorder=None,
                   candidates: int = 1,
                   role_ttl: Optional[float] = None,
                   coordinator_kill=None,
                   control=None,
                   autoscale=None) -> "Fleet":
        """A fleet over an InProcessBroker: assigned consumers with the
        coordinator's commit fence, group-lag drain signal, one shared
        scoring pipeline, and (with ``sched_config``) a per-worker adaptive
        scheduler shedding against the fleet's global backlog watermark.

        ``fault_plan`` (stream/faults.py FaultPlan, e.g. from the scenario
        harness — docs/scenarios.md) wraps every worker's transport in the
        chaos layer. Only NON-LETHAL fault kinds belong here (duplicates,
        corruption, latency spikes, commit fences, lossy flushes): a poll
        transport error or flush crash raises out of the worker thread and
        counts as a worker error — scripted whole-worker deaths are
        ``death_plan``'s job."""
        from fraud_detection_tpu.stream.engine import StreamingClassifier

        fleet_holder: dict = {}
        schedulers: dict = {}

        def make_consumer(lease):
            coordinator = fleet_holder["fleet"].coordinator
            consumer = broker.assigned_consumer(
                lease.partitions, group_id,
                fence=lambda pairs, wid=lease.worker_id:
                    coordinator.fence_lost(wid, pairs))
            # Chaos wraps INSIDE the fleet's poll-path wrapper, so the
            # death plan / heartbeat hooks still fire even when a poll's
            # result is chaos-mangled.
            return (fault_plan.consumer(consumer)
                    if fault_plan is not None else consumer)

        def make_engine(consumer, worker_id):
            scheduler = None
            if sched_config is not None:
                from fraud_detection_tpu.sched import AdaptiveScheduler

                # One scheduler per worker, shared across its incarnations
                # (same contract as serve.py --workers): incarnations run
                # sequentially, so the single-driver region holds.
                scheduler = schedulers.get(worker_id)
                if scheduler is None:
                    scheduler = AdaptiveScheduler(sched_config, batch_size)
                    bus = fleet_holder["fleet"].bus
                    scheduler.fleet_backlog = (
                        lambda b=bus: (b.fleet_view() or {}).get(
                            "backlog_per_worker"))
                    schedulers[worker_id] = scheduler
            producer = broker.producer()
            if fault_plan is not None:
                producer = fault_plan.producer(producer)
            return StreamingClassifier(
                pipeline, consumer, producer, output_topic,
                batch_size=batch_size, max_wait=max_wait,
                pipeline_depth=pipeline_depth,
                async_dispatch=async_dispatch,
                scheduler=scheduler, dlq_topic=dlq_topic,
                # One tracer per worker, shared across incarnations —
                # chains and stage sketches survive rebalances exactly
                # like the scheduler's SLO window does.
                rowtrace=fleet_holder["fleet"].tracers.get(worker_id),
                # One sentinel per worker, same sharing contract: alert
                # state and incident accounting survive rebalances.
                sentinel=fleet_holder["fleet"].worker_sentinels.get(
                    worker_id))

        fleet = cls(
            n_workers, make_engine, make_consumer,
            topics=[input_topic], num_partitions=broker.num_partitions,
            bus=FleetBus(dir=bus_dir), lease_ttl=lease_ttl,
            lag_fn=lambda: broker.group_lag(group_id, [input_topic]),
            death_plan=death_plan, heartbeat_interval=heartbeat_interval,
            tick_interval=tick_interval, health_file=health_file,
            trace=trace, trace_sample=trace_sample, trace_seed=trace_seed,
            sentinel_rules=sentinel_rules,
            worker_sentinel_rules=worker_sentinel_rules,
            sentinel_clock=sentinel_clock,
            sentinel_recorder=sentinel_recorder,
            candidates=candidates, role_ttl=role_ttl,
            coordinator_kill=coordinator_kill, control=control,
            autoscale=autoscale)
        fleet_holder["fleet"] = fleet
        return fleet

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def stop(self) -> None:
        """Cooperative shutdown: every worker drains + commits and leaves.
        The latch is set FIRST so a racing scale-out refuses instead of
        launching a worker nobody will stop."""
        self._stop.set()
        with self._registry:
            workers = list(self.workers)
        for w in workers:
            w.stop()

    def fleet_health(self) -> dict:
        """Monitor-thread-safe aggregate: the coordinator's last view plus
        every live worker's engine health (the ``--fleet-health-file``
        payload and the serve CLI's exit report)."""
        with self._registry:
            workers = list(self.workers)
        return {
            "time": time.time(),
            "fleet": self.coordinator.last_view(),
            "alerts": (self.sentinel.snapshot()
                       if self.sentinel is not None else None),
            "workers": {w.worker_id: {**w.result(), "health": w.health()}
                        for w in workers},
        }

    def _write_health_file(self) -> None:
        path = self.health_file
        if path is None:
            return
        # Shared atomic writer: failures swallowed inside (health
        # reporting must never kill serving).
        atomic_write_json(path, self.fleet_health())

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.tick_interval):
            try:
                self.coordinator.tick()
            except Exception:  # noqa: BLE001 — the tick must keep ticking
                log.exception("fleet coordinator tick failed")
            if self.sentinel is not None:
                # Coordinator-level rules judged on the view the tick just
                # aggregated (evaluate() guards its own failures).
                self.sentinel.evaluate()
            if self.autoscaler is not None:
                # Elasticity judged AFTER the sentinel pass: the policy
                # sees exactly the signal state this tick produced.
                try:
                    self.autoscaler.step()
                except Exception:  # noqa: BLE001 — sizing must not kill
                    log.exception("fleet autoscaler step failed")
            self._write_health_file()

    def _candidate_main(self, cid: str) -> None:
        """One coordinator candidate's contention loop (fleet/control.py):
        poll the role lease for vacancy (stale beacon past role_ttl, or
        an abdication) and elect when it opens. Harmless while standby —
        ``step`` is a no-op for a live incumbent."""
        coordinator = self.coordinator
        interval = max(0.01, coordinator.role_ttl / 8.0)
        while not self._stop.wait(interval):
            try:
                coordinator.step(cid)
            except Exception:  # noqa: BLE001 — candidates must keep running
                log.exception("fleet candidate %s election pass failed", cid)

    def _worker_main(self, worker: FleetWorker,
                     idle_timeout: Optional[float]) -> None:
        try:
            worker.run(idle_timeout=idle_timeout)
        except BaseException as e:  # noqa: BLE001 — surfaced via results
            if worker.error is None:
                worker.error = e
            log.warning("fleet worker %s died: %r (survivors take over "
                        "its partitions)", worker.worker_id, e)

    def run(self, idle_timeout: Optional[float] = 1.0,
            join_timeout: Optional[float] = None) -> dict:
        """Run the whole fleet; returns the merged stats dict. With
        ``idle_timeout`` set this is a drain run (workers exit once input
        is idle AND the group's committed lag is zero — see
        FleetWorker.run); None serves until ``stop()``."""
        if self.death_plan is not None:
            # Deterministic arming order — the seeded plan draws per ARM,
            # so victims must not depend on thread start races.
            for w in self.workers:
                self.death_plan.arm(w.worker_id)
        # Scaled-out workers inherit this run's drain semantics (the
        # provisioner spawns with the same idle_timeout).
        with self._registry:
            self._idle_timeout = idle_timeout
        t0 = time.perf_counter()
        monitor = threading.Thread(target=self._monitor_loop,
                                   name="fleet-monitor", daemon=True)
        monitor.start()
        candidate_threads: List[threading.Thread] = []
        if hasattr(self.coordinator, "candidate_ids"):
            candidate_threads = [
                threading.Thread(target=self._candidate_main, args=(cid,),
                                 name=f"fleet-candidate-{cid}", daemon=True)
                for cid in self.coordinator.candidate_ids]
            for t in candidate_threads:
                t.start()
        with self._registry:
            self._threads = [
                threading.Thread(target=self._worker_main,
                                 args=(w, idle_timeout),
                                 name=f"fleet-{w.worker_id}", daemon=True)
                for w in self.workers]
            threads = list(self._threads)
        for t in threads:
            t.start()
        try:
            # The join loop re-snapshots the registry each pass: the
            # autoscaler grows ``_threads`` from the monitor thread, and
            # a scaled-out worker is as load-bearing as a founding one.
            deadline = (time.perf_counter() + join_timeout
                        if join_timeout is not None else None)
            while True:
                with self._registry:
                    threads = list(self._threads)
                alive = [t for t in threads if t.is_alive()]
                if not alive:
                    break
                if deadline is not None and time.perf_counter() >= deadline:
                    break
                alive[0].join(min(0.2, self.tick_interval * 4))
        except KeyboardInterrupt:
            # Operator shutdown: drain + leave gracefully (partitions
            # reassign immediately; nothing waits out a lease ttl).
            self.stop()
            with self._registry:
                threads = list(self._threads)
            for t in threads:
                t.join(timeout=30.0)
        finally:
            self._stop.set()
            monitor.join(timeout=5.0)
            for t in candidate_threads:
                t.join(timeout=5.0)
            # A scale-out racing the loop's exit: the latch above stops
            # further launches; whatever landed still gets drained.
            with self._registry:
                threads = list(self._threads)
            for t in threads:
                if t.is_alive():
                    t.join(timeout=5.0)
        wall = time.perf_counter() - t0
        try:
            final_view = self.coordinator.tick()   # post-run aggregate
        except Exception:  # noqa: BLE001
            final_view = self.coordinator.last_view()
        self._write_health_file()
        total = StreamStats()
        with self._registry:
            workers = list(self.workers)
        for w in workers:
            _merge_stats(total, w.stats)
        total.elapsed = wall     # workers overlap: wall-clock, not the sum
        deaths = [w.result() for w in workers if w.death is not None]
        errors = [w.result() for w in workers if w.error is not None]
        out = {
            **total.as_dict(),
            "workers": len(workers),
            "per_worker": [w.result() for w in workers],
            "per_worker_processed": [w.stats.processed
                                     for w in workers],
            "incarnations": sum(w.incarnations for w in workers),
            "rebalances": self.coordinator.rebalances,
            "lease_expirations": self.coordinator.expirations,
            "deaths": deaths,
            "errors": [e["error"] for e in errors],
            "fleet": final_view,
        }
        if self.death_plan is not None:
            out["death_plan"] = self.death_plan.report()
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.report()
        if hasattr(self.coordinator, "succession_report"):
            succession = self.coordinator.succession_report()
            if self.coordinator_kill is not None:
                succession["kill_plan"] = self.coordinator_kill.report()
            out["succession"] = succession
        if self.sentinel is not None:
            # Final pass AFTER the post-run tick above, so membership
            # drops and last-tick watermarks are judged before the
            # snapshot lands in the merged stats. (Worker sentinels got
            # their last pass on their final poll; their engines are gone
            # now, so another pass would only count a source error.)
            self.sentinel.evaluate()
            out["alerts"] = self.sentinel.snapshot()
            out["worker_alerts"] = {
                wid: {k: snap[k] for k in ("firing", "critical_firing",
                                           "fired", "resolved",
                                           "still_firing")}
                for wid, snap in ((wid, s.snapshot())
                                  for wid, s in
                                  self.worker_sentinels.items())}
        if self.tracers:
            # Final fleet-level stage attribution straight from the
            # tracers (the post-drain coordinator tick sees no members —
            # workers retract their bus docs as they leave); lossless, so
            # it equals a single-process run over the same samples.
            from fraud_detection_tpu.obs.trace import fleet_stage_latency

            out["stage_latency_ms"] = fleet_stage_latency(
                [t.stages_wire() for t in self.tracers.values()])
        return out
