"""Fleet smoke check: ``python -m fraud_detection_tpu.fleet.smoke``.

The CI fleet gate (and a handy local sanity command): run the smoke corpus
through a 1-worker and an N-worker in-process fleet, then a seeded
worker-kill run, and assert the invariants that define the fleet lane:

* exact key-set accounting on both drains (every input key classified
  exactly once — zero loss, zero duplicates);
* zero loss / zero duplicates ACROSS a seeded worker death + rebalance;
* aggregate throughput >= ``FLEET_SMOKE_MIN_SCALING`` x the single-worker
  rate — asserted only when the machine has >= 2 usable cores (thread
  workers cannot parallelize compute on one core; the measured ratio is
  always printed and committed either way).

Exit 0 = all invariants hold; nonzero prints the failing invariant.
"""

from __future__ import annotations

import json
import os
import sys


def _drain(pipeline, n_msgs: int, n_workers: int, texts, *,
           death_plan=None, num_partitions: int = 4, batch_size: int = 256):
    from fraud_detection_tpu.fleet import Fleet
    from fraud_detection_tpu.stream import InProcessBroker

    broker = InProcessBroker(num_partitions=num_partitions)
    feeder = broker.producer()
    for i in range(n_msgs):
        feeder.produce("in", json.dumps(
            {"text": texts[i % len(texts)], "id": i}).encode(),
            key=str(i).encode())
    fleet = Fleet.in_process(broker, pipeline, "in", "out", n_workers,
                             batch_size=batch_size, death_plan=death_plan,
                             lease_ttl=1.0)
    result = fleet.run(idle_timeout=0.5, join_timeout=120.0)
    out_keys = [m.key for m in broker.messages("out")]
    return result, out_keys


def main() -> int:
    from fraud_detection_tpu.data import generate_corpus
    from fraud_detection_tpu.models.pipeline import synthetic_demo_pipeline
    from fraud_detection_tpu.stream.faults import WorkerDeathPlan

    n_msgs = int(os.environ.get("FLEET_SMOKE_MSGS", "6000"))
    n_workers = int(os.environ.get("FLEET_SMOKE_WORKERS", "2"))
    min_scaling = float(os.environ.get("FLEET_SMOKE_MIN_SCALING", "1.5"))
    corpus = generate_corpus(n=500, seed=11)
    texts = [d.text for d in corpus]
    pipeline = synthetic_demo_pipeline(256, n=400, seed=7,
                                       num_features=4096)
    pipeline.predict(texts[:256])    # compile off the measured path
    expect = {str(i).encode() for i in range(n_msgs)}

    single, keys1 = _drain(pipeline, n_msgs, 1, texts)
    if sorted(keys1) != sorted(expect):
        print(f"FAIL: 1-worker drain key accounting "
              f"(got {len(keys1)} keys, want {n_msgs} exactly once)")
        return 1
    multi, keys_n = _drain(pipeline, n_msgs, n_workers, texts)
    if sorted(keys_n) != sorted(expect):
        print(f"FAIL: {n_workers}-worker drain key accounting "
              f"(got {len(keys_n)} keys, want {n_msgs} exactly once)")
        return 1

    plan = WorkerDeathPlan(seed=5, kills=1, min_polls=2, max_polls=6)
    chaos, keys_c = _drain(pipeline, n_msgs, n_workers, texts,
                           death_plan=plan)
    dup = len(keys_c) - len(set(keys_c))
    lost = len(expect - set(keys_c))
    if lost or dup or not chaos["deaths"]:
        print(f"FAIL: worker-kill rebalance (lost={lost} dup={dup} "
              f"deaths={chaos['deaths']})")
        return 1

    scaling = (multi["msgs_per_sec"] / single["msgs_per_sec"]
               if single["msgs_per_sec"] else 0.0)
    cores = os.cpu_count() or 1
    report = {
        "workers": n_workers,
        "cores": cores,
        "single_worker_msgs_per_s": single["msgs_per_sec"],
        "aggregate_msgs_per_s": multi["msgs_per_sec"],
        "scaling_x": round(scaling, 3),
        "kill": chaos["death_plan"],
        "rebalances": chaos["rebalances"],
        "lease_expirations": chaos["lease_expirations"],
    }
    print(json.dumps(report))
    if cores >= 2 and scaling < min_scaling:
        print(f"FAIL: aggregate {scaling:.2f}x single-worker on {cores} "
              f"cores (want >= {min_scaling}x)")
        return 1
    if cores < 2:
        print(f"note: {cores} core(s) — thread workers cannot parallelize "
              f"compute here; scaling assert skipped, invariants held")
    return 0


if __name__ == "__main__":
    sys.exit(main())
