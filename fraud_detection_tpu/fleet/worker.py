"""FleetWorker: one engine incarnation chain under a partition lease.

A fleet worker owns the worker-side half of the rebalance protocol
(docs/fleet.md): it joins the coordinator, consumes EXACTLY its leased
partitions through the broker's manual-assignment mode, heartbeats on the
poll path, publishes its health + local backlog on the fleet bus, and —
when a sync shows its lease changed — stops the current engine incarnation,
lets the engine's own shutdown path drain and commit every in-flight batch,
closes the consumer, ACKs the release barrier, and rebuilds on the new
lease. Worker death (the chaos harness's :class:`WorkerKilled`, or any
crash) propagates out of the poll path *before* a new batch dispatches, so
the dead incarnation leaves nothing produced-but-uncommitted: the
partitions' next owner resumes from the committed offsets with zero loss
and zero duplicates (tests/test_fleet.py pins the exact key-set accounting).

Threading: ``run()`` is the worker thread's single entry (one engine driver
per worker — the engine's own drive region guards it); ``stop()`` and
``result()`` are the cross-thread surface (lock-free latch + snapshot,
mirroring the engine's contract).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from fraud_detection_tpu.stream.engine import StreamStats, _merge_stats
from fraud_detection_tpu.stream.faults import WorkerKilled
from fraud_detection_tpu.utils.racecheck import ExclusiveRegion


class _FleetConsumer:
    """Consumer wrapper riding the worker's poll path: fires the seeded
    death plan, heartbeats the coordinator lease, and publishes the bus doc
    on a time cadence — all on the engine driver thread, so the lease stays
    exactly as live as the worker's actual consumption (Kafka's
    poll-is-liveness model)."""

    def __init__(self, inner, worker: "FleetWorker"):
        self.inner = inner
        self._worker = worker

    def poll(self, timeout: float = 1.0):
        self._worker._on_poll(self.inner)
        return self.inner.poll(timeout)

    def poll_batch(self, max_messages: int, timeout: float):
        self._worker._on_poll(self.inner)
        return self.inner.poll_batch(max_messages, timeout)

    def commit(self) -> None:
        self.inner.commit()

    def commit_offsets(self, offsets) -> None:
        self.inner.commit_offsets(offsets)

    def backlog(self) -> int:
        return self.inner.backlog()

    def close(self) -> None:
        self.inner.close()

    def __getattr__(self, name):
        return getattr(self.inner, name)


class FleetWorker:
    """One fleet member: lease -> consumer -> engine, rebuilt per rebalance."""

    def __init__(self, worker_id: str, coordinator, bus,
                 make_engine: Callable, make_consumer: Callable, *,
                 death_plan=None, heartbeat_interval: float = 0.2,
                 rowtrace=None, sentinel=None, clock=time.monotonic):
        if heartbeat_interval <= 0:
            raise ValueError(
                f"heartbeat_interval must be > 0, got {heartbeat_interval}")
        self.worker_id = worker_id
        self.coordinator = coordinator
        self.bus = bus
        # make_consumer(lease) -> broker consumer over lease.partitions;
        # make_engine(consumer, worker_id) -> StreamingClassifier.
        self.make_engine = make_engine
        self.make_consumer = make_consumer
        self.death_plan = death_plan
        self.heartbeat_interval = heartbeat_interval
        # Optional obs.trace.RowTracer shared by this worker's engine
        # incarnations (Fleet wires the SAME tracer into make_engine):
        # every bus publish then carries the worker's per-stage sketch
        # wires, which the coordinator merges losslessly into fleet-level
        # p50/p99 per stage (docs/observability.md).
        self.rowtrace = rowtrace
        # Optional obs.sentinel.Sentinel watching THIS worker's engine
        # health: evaluated on the poll path at heartbeat cadence (the
        # same rate-limit gate as the coordinator sync), its alert state
        # rides every bus doc so the coordinator's tick aggregates
        # fleet-wide firing counts (docs/observability.md).
        self.sentinel = sentinel
        self._clock = clock
        self.stats = StreamStats()
        self.incarnations = 0
        self.death: Optional[WorkerKilled] = None
        self.error: Optional[BaseException] = None
        self._lease = None
        self._engine = None
        self._stopped = False
        self._last_sync = 0.0
        # One thread drives a worker's incarnation chain by contract —
        # stop()/result()/health() are the cross-thread surface. The region
        # turns a second concurrent run() into a RaceError instead of
        # silently interleaving two engines on one lease.
        self._region = ExclusiveRegion("FleetWorker.run")

    # ------------------------------------------------------------------
    # cross-thread surface
    # ------------------------------------------------------------------

    def stop(self) -> None:
        """Request shutdown (lock-free latch, same contract as the
        engine's): the current incarnation drains + commits and the worker
        leaves the fleet gracefully."""
        self._stopped = True    # flightcheck: ignore[FC102] — documented lock-free latch
        engine = self._engine
        if engine is not None:
            engine.stop()

    def result(self) -> dict:
        """Cross-thread progress snapshot (racy reads of monotonic state)."""
        return {
            "worker_id": self.worker_id,
            "processed": self.stats.processed,
            "incarnations": self.incarnations,
            "dead": None if self.death is None else self.death.mode,
            "error": repr(self.error) if self.error is not None else None,
        }

    def health(self) -> Optional[dict]:
        """The live incarnation's engine health (None between engines)."""
        engine = self._engine
        return engine.health() if engine is not None else None

    # ------------------------------------------------------------------
    # worker thread
    # ------------------------------------------------------------------

    def _on_poll(self, consumer) -> None:
        """Per-poll hook on the driver thread: death plan, heartbeat,
        bus publish, rebalance detection (stops the engine so the outer
        loop rebuilds on the new lease)."""
        if self.death_plan is not None:
            self.death_plan.tick(self.worker_id)    # raises WorkerKilled
        now = self._clock()
        if now - self._last_sync < self.heartbeat_interval:
            return
        self._last_sync = now
        if self.sentinel is not None:
            # Heartbeat-cadence evaluation on the driver thread, BEFORE
            # the publish below, so the bus doc carries this pass's state.
            self.sentinel.evaluate()
        lease = self.coordinator.sync(self.worker_id)
        self._publish(consumer)
        cur = self._lease
        if cur is not None and lease.generation != cur.generation:
            if (set(lease.partitions) != set(cur.partitions)
                    or lease.pending or lease.released):
                # Our ownership changed (or partitions are waiting on a
                # peer's drain): end this incarnation. The engine's
                # shutdown path drains + commits in-flight batches; the
                # outer loop then acks and rebuilds — the worker half of
                # revoke->drain->commit->reassign.
                engine = self._engine
                if engine is not None:
                    engine.stop()
            else:
                # Uninvolved survivor: same partitions, new generation —
                # keep running (sticky assignment's whole point).
                self._lease = lease

    def _publish(self, consumer, engine_health: Optional[dict] = None) -> None:
        if self.bus is None:
            return
        lease = self._lease
        try:
            backlog = consumer.backlog() if consumer is not None else None
        except Exception:  # noqa: BLE001 — observability must not kill serving
            backlog = None
        if engine_health is None:
            engine = self._engine
            engine_health = engine.health() if engine is not None else None
        self.bus.publish(self.worker_id, {
            "worker_id": self.worker_id,
            "generation": lease.generation if lease is not None else None,
            "partitions": ([list(p) for p in lease.partitions]
                           if lease is not None else []),
            "backlog": backlog,
            "dead": None if self.death is None else self.death.mode,
            "engine": engine_health,
            # Lossless per-stage sketch wires for the coordinator's
            # fleet-level stage-latency merge (None when not tracing).
            "obs": ({"stages": self.rowtrace.stages_wire()}
                    if self.rowtrace is not None else None),
            # This worker's alert state (obs/sentinel/): the compact
            # subset the coordinator aggregates — full incident history
            # stays in the worker's own health()["alerts"] block.
            "alerts": (self._alerts_doc()
                       if self.sentinel is not None else None),
        })

    def _alerts_doc(self) -> dict:
        snap = self.sentinel.snapshot()
        return {"firing": snap["firing"],
                "critical_firing": snap["critical_firing"],
                "fired": snap["fired"],
                "resolved": snap["resolved"]}

    def run(self, idle_timeout: Optional[float] = None) -> StreamStats:
        """Drive engine incarnations until stopped, killed, or — when
        ``idle_timeout`` is set (drain runs) — the input is idle AND the
        fleet's committed lag is clear (a dead peer's unreassigned backlog
        keeps survivors alive until its lease expires and the partitions
        reach them)."""
        with self._region:
            return self._run(idle_timeout)

    def _run(self, idle_timeout: Optional[float]) -> StreamStats:
        lease = self.coordinator.join(self.worker_id)
        if self.death_plan is not None:
            self.death_plan.arm(self.worker_id)
        graceful_exit = False
        try:
            while not self._stopped:
                self._lease = lease
                inner = self.make_consumer(lease)
                engine = self._engine = self.make_engine(
                    _FleetConsumer(inner, self), self.worker_id)
                self.incarnations += 1
                try:
                    stats = engine.run(idle_timeout=idle_timeout)
                except WorkerKilled as e:
                    # Seeded whole-worker death: nothing produced past the
                    # last commit (the kill fires at poll time and the
                    # engine's abort path discards unproduced in-flight
                    # batches). Graceful deaths release the lease NOW;
                    # crashes just vanish and the lease must expire.
                    self.death = e
                    _merge_stats(self.stats, engine.stats)
                    self._publish(None, engine_health=engine.health())
                    return self.stats
                finally:
                    inner.close()
                _merge_stats(self.stats, stats)
                # Incarnation fully drained + committed: release anything
                # the last rebalance revoked from us.
                lease = self.coordinator.ack(self.worker_id)
                if lease.released:
                    # Coordinator-requested voluntary leave (scale-in,
                    # fleet/autoscale/): the engine shutdown above drained
                    # + committed everything, the ack dropped our barrier
                    # holds — exit so the finally block leaves the fleet
                    # and retracts our bus doc. Drain-before-release is
                    # the checker's release_before_drain obligation.
                    graceful_exit = True
                    break
                if self._stopped:
                    graceful_exit = True
                    break
                if (lease.generation != (self._lease.generation
                                         if self._lease else -1)
                        and (set(lease.partitions)
                             != set(self._lease.partitions)
                             or lease.pending)):
                    continue    # rebuild on the changed lease
                if idle_timeout is None:
                    continue    # serve-forever: only stop()/death end us
                lag = self.coordinator.committed_lag()
                if lag is None or lag <= 0:
                    graceful_exit = True
                    break
                # Input looks idle from OUR partitions but the fleet still
                # owes committed work (e.g. a dead peer's partitions are
                # waiting out their lease): stay up, poll again.
            else:
                graceful_exit = True
        except BaseException as e:  # noqa: BLE001 — surfaced via result()
            self.error = e
            engine = self._engine
            if engine is not None:
                _merge_stats(self.stats, engine.stats)
            raise
        finally:
            self._engine = None
            if self.death is None:
                # Normal/stop()/error exits all drained via the engine's
                # own shutdown path — leave gracefully so partitions
                # reassign immediately instead of waiting out the ttl.
                self.coordinator.leave(self.worker_id)
                self._publish(None)
                if graceful_exit and self.bus is not None:
                    self.bus.retract(self.worker_id)
            elif self.death.mode == "graceful":
                self.coordinator.leave(self.worker_id)
        return self.stats
