"""Driftloop — closed-loop online learning beside the serving engine.

Four pieces (docs/online_learning.md):

* the **label lane**: delayed ground-truth labels on a feedback topic
  (stream/feedback.py format, any Consumer transport), joined against a
  bounded keyed sliding window of recently scored rows
  (:class:`~fraud_detection_tpu.learn.store.WindowStore` — packed encoded
  features retained, never text; every label joined, expired, or counted);
* the **incremental trainer**: windowed warm-started boosted-tree refresh
  through the device histogram kernels
  (models/train_trees.py ``refresh_gradient_boosting``), producing a
  registry-publishable candidate with lineage + window metadata;
* the **loop controller**: the registered "learn-lane" thread
  (:class:`~fraud_detection_tpu.learn.loop.LearnLoop`) joining labels,
  triggering retrains on row-count/time/drift signals, publishing to the
  registry — promotion rides the EXISTING ``LifecycleController``
  stage→shadow→judge→promote path and its PSI/agreement/health gates;
* **closed-loop verification**: the seeded ``drift_shift`` game day
  (scenarios/gameday.py) gating detection→retrain→promotion latency,
  exact label-join accounting, and zero-loss/zero-dup through the swap.
"""

from fraud_detection_tpu.learn.loop import LearnConfig, LearnLoop
from fraud_detection_tpu.learn.store import StoredRow, WindowStore

__all__ = ["LearnConfig", "LearnLoop", "StoredRow", "WindowStore"]
