"""LearnLoop: the registered "learn-lane" thread closing the learning loop.

One worker thread runs beside the serving engine and owns four jobs
(docs/online_learning.md):

1. **Ingest scored rows.** The engine offers each scored micro-batch's
   source coordinates + payloads + primary results through a non-blocking
   bounded queue (``submit`` — the same drop-and-count contract as the
   shadow scorer, registry/shadow.py). The lane decodes and re-encodes the
   texts OFF the hot path and inserts the packed rows into the
   :class:`~fraud_detection_tpu.learn.store.WindowStore`; raw text is
   dropped the moment the packed form exists.
2. **Join labels.** The lane polls the feedback topic (any ``Consumer``;
   stream/feedback.py is the record format), joining each label against
   the window — every label ends joined, expired, or missed, and the
   offsets commit after processing (at-least-once; duplicate labels
   re-join harmlessly).
3. **Retrain on signal.** Three triggers — drift (windowed label-error
   rate over threshold: the live model is WRONG about recent ground
   truth), row count (enough fresh labels), and time (optional cadence) —
   fire a warm-started boosted-tree refresh
   (models/train_trees.py ``refresh_gradient_boosting``: the active
   model's trees + a few new rounds on the window, bucketed shapes so XLA
   compiles stay off the steady state). The candidate publishes to the
   registry with lineage + window metadata in the manifest.
4. **Ride the lifecycle.** Promotion is NOT this loop's decision: the
   existing ``LifecycleController`` (registry/promote.py) stages the
   published version, shadow-scores it, and judges it through the PR 2
   PSI/agreement/health gates — every transition audited. The loop only
   observes (``on_transition``) and, when its candidate is staged,
   REPLAYS the recent window to the shadow scorer (``submit_encoded``) so
   the candidate is judged against the rows that motivated it without
   waiting for future traffic. If a PROMOTED candidate then regresses
   against fresh ground truth, the loop rolls back through the
   controller's audited ``rollback`` path.

The thread is registered in analysis/entrypoints.py ("learn-lane") with an
ExclusiveRegion tripwire; every mutable counter lives under one lock
(``snapshot()`` is the engine's ``health()["learn"]`` block, FC301-pinned
against tests/test_learn.py ``LEARN_BLOCK_SCHEMA``).
"""

from __future__ import annotations

import json
import queue
import random
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from fraud_detection_tpu.learn.store import WindowStore
from fraud_detection_tpu.stream.feedback import parse_label
from fraud_detection_tpu.utils import get_logger
from fraud_detection_tpu.utils.racecheck import ExclusiveRegion

log = get_logger("learn.loop")


@dataclass(frozen=True)
class LearnConfig:
    """Knobs of the closed loop (docs/online_learning.md documents each).

    The DRIFT trigger is the headline: the windowed label-error rate of
    the live model over the most recent ``error_window`` labeled rows
    exceeding ``error_threshold`` means recent ground truth disagrees
    with what was served — fraud drifted. ``rows_trigger`` (fresh joins)
    and ``interval_s`` (cadence, off by default) are the supporting
    signals. ``cooldown_s`` bounds retrain churn."""

    window: int = 8192              # WindowStore capacity (rows)
    max_age_s: float = 3600.0       # WindowStore age bound
    min_labeled: int = 256          # evidence floor for ANY retrain
    min_new_labels: int = 64        # fresh joins required since last retrain
    error_threshold: float = 0.15   # drift trigger: recent label-error rate
    error_window: int = 512         # labeled rows the drift trigger judges
    rows_trigger: Optional[int] = None   # fresh-join count trigger (off=None)
    interval_s: Optional[float] = None   # time trigger (off=None)
    cooldown_s: float = 2.0         # min seconds between retrains
    refresh_rounds: int = 8         # new boosting rounds per retrain
    max_train_rows: int = 4096      # densified window cap (most recent)
    max_trees: int = 400            # past this, warm-start from the base
    queue: int = 64                 # scored-batch submit queue bound
    sample: float = 1.0             # fraction of batches ingested
    poll_timeout_s: float = 0.02    # feedback poll wait per tick
    replay_shadow: bool = True      # feed staged candidates the window
    replay_rows: int = 2048         # most recent rows replayed to shadow
    rollback_error_rate: Optional[float] = 0.5  # promoted-regression bound
    rollback_min_labeled: int = 64  # evidence floor for a rollback

    def __post_init__(self):
        if not 0.0 < self.sample <= 1.0:
            raise ValueError(f"sample must be in (0, 1], got {self.sample}")
        if self.min_labeled < 2:
            raise ValueError(
                f"min_labeled must be >= 2, got {self.min_labeled}")
        if self.error_threshold <= 0:
            raise ValueError(
                f"error_threshold must be > 0, got {self.error_threshold}")
        if self.refresh_rounds < 1:
            raise ValueError(
                f"refresh_rounds must be >= 1, got {self.refresh_rounds}")


class LearnLoop:
    """See module docstring. ``feedback_consumer`` is any Consumer on the
    feedback topic; ``registry``/``hotswap`` are the serving lifecycle the
    loop publishes into; ``shadow`` (optional) receives window replays for
    staged candidates; ``controller`` (optional) enables regression
    rollback. ``clock`` paces cooldowns (wall monotonic); ``now_fn``
    stamps events (virtual seconds under the scenario harness)."""

    def __init__(self, *, store: Optional[WindowStore] = None,
                 feedback_consumer=None, registry=None, hotswap=None,
                 shadow=None, controller=None,
                 config: Optional[LearnConfig] = None,
                 text_field: str = "text",
                 clock=time.monotonic, now_fn=None,
                 rng: Optional[random.Random] = None,
                 start: bool = True):
        self.config = cfg = config or LearnConfig()
        self.store = store if store is not None else WindowStore(
            cfg.window, max_age_s=cfg.max_age_s, clock=clock)
        self._consumer = feedback_consumer
        self._registry = registry
        self._hotswap = hotswap
        self._shadow = shadow
        self._controller = controller
        self._text_field = text_field
        self._clock = clock
        self._now = now_fn if now_fn is not None else clock
        self._rng = rng if rng is not None else random.Random()
        self._queue: "queue.Queue" = queue.Queue(maxsize=cfg.queue)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._featurizer = None
        # -- counters (all under _lock) --
        self._submitted = 0
        self._dropped = 0
        self._sampled_out = 0
        self._encode_errors = 0
        self._labels_polled = 0
        self._triggered = 0
        self._published = 0
        self._failed = 0
        self._in_flight = False
        self._promoted = 0
        self._rejected = 0
        self._rolled_back = 0
        self._published_versions: List[int] = []
        self._promoted_versions: List[int] = []
        self._last_trigger: Optional[str] = None
        self._first_trigger_at: Optional[float] = None
        self._promoted_at: Optional[float] = None
        self._last_retrain_clock: Optional[float] = None
        self._joined_at_last_retrain = 0
        self._last_retrain_wall: Optional[float] = None
        self._retrain_wall_total = 0.0
        self._candidate_error: Optional[float] = None
        self._primary_error: Optional[float] = None
        self._replay_pending: Optional[int] = None
        self._replayed: set = set()
        self._rollback_done: set = set()
        self._base_model = None   # first active ensemble (growth-cap base)
        # Race tripwire (utils/racecheck.py): the lane is single-worker by
        # construction — one thread started here, never respawned; tick()
        # is also the test-mode inline driver (start=False), and the
        # region makes a second concurrent driver a loud RaceError.
        self._region = ExclusiveRegion("LearnLoop.lane")
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="learn-lane")
            self._thread.start()

    # ------------------------------------------------------------------
    # hot-path surface (engine driver)
    # ------------------------------------------------------------------

    def bind_controller(self, controller) -> None:
        """Late-bind the LifecycleController (construction order: the
        controller wants ``on_transition=loop.on_transition``, the loop
        wants the controller for regression rollback — bind whichever is
        built second through this)."""
        with self._lock:
            self._controller = controller

    def wants(self) -> bool:
        """Cheap per-batch gate (sampling draw; sampled-out counted)."""
        if self.config.sample >= 1.0 or self._rng.random() < self.config.sample:
            return True
        with self._lock:
            self._sampled_out += 1
        return False

    def submit(self, coords: Sequence[Tuple[str, int, int]],
               payloads: Sequence, labels, probs, *, raw: bool,
               version: Optional[int] = None) -> bool:
        """Queue one scored micro-batch for window ingestion. ``coords``
        are each row's (topic, partition, offset); ``payloads`` are raw
        message bytes (``raw=True``) or decoded texts, positionally
        aligned. NEVER blocks: a full queue drops the batch and counts it
        — the window is a sample under overload, and the accounting says
        so."""
        item = (list(coords), list(payloads), np.asarray(labels),
                np.asarray(probs, np.float64), bool(raw), version)
        try:
            self._queue.put_nowait(item)
        except queue.Full:
            with self._lock:
                self._dropped += 1
            return False
        with self._lock:
            self._submitted += 1
        return True

    # ------------------------------------------------------------------
    # lane worker
    # ------------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                with self._region:
                    progressed = self._tick_locked()
            except Exception as e:  # noqa: BLE001 — the lane must survive
                log.warning("learn-lane tick failed: %s", e, exc_info=True)
                progressed = False
            if not progressed:
                self._stop.wait(0.01)

    def tick(self) -> bool:
        """One inline lane step (tests and the demo drive this with
        ``start=False``); returns whether any work was done."""
        with self._region:
            return self._tick_locked()

    def _tick_locked(self) -> bool:
        progressed = self._drain_scored()
        progressed |= self._poll_labels()
        self.store.sweep()
        progressed |= self._maybe_retrain()
        progressed |= self._maybe_replay()
        self._maybe_rollback()
        return progressed

    # -- ingestion ------------------------------------------------------

    def _drain_scored(self, max_batches: int = 16) -> bool:
        did = False
        for _ in range(max_batches):
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            did = True
            try:
                self._ingest(item)
            except Exception as e:  # noqa: BLE001 — poison batch, counted
                with self._lock:
                    self._encode_errors += 1
                log.warning("learn ingest failed: %s", e)
            finally:
                self._queue.task_done()
        return did

    def _featurizer_now(self):
        if self._featurizer is None:
            pipe = self._hotswap
            feat = getattr(pipe, "featurizer", None)
            if feat is None:
                raise RuntimeError("learn loop needs a pipeline featurizer")
            self._featurizer = feat
        return self._featurizer

    def _ingest(self, item) -> None:
        coords, payloads, labels, probs, raw, version = item
        texts: List[Optional[str]] = []
        if raw:
            for value in payloads:
                try:
                    obj = json.loads(value)
                except ValueError:
                    texts.append(None)
                    continue
                t = obj.get(self._text_field) if isinstance(obj, dict) else None
                texts.append(t if isinstance(t, str) else None)
        else:
            texts = [t if isinstance(t, str) else None for t in payloads]
        keep = [i for i, t in enumerate(texts) if t is not None]
        if not keep:
            return
        feat = self._featurizer_now()
        enc = feat.encode([texts[i] for i in keep],
                          batch_size=len(keep))
        ids = np.asarray(enc.ids)
        counts = np.asarray(enc.counts)
        labels_l = np.asarray(labels)[keep].tolist()
        probs_l = np.asarray(probs)[keep].tolist()
        for j, i in enumerate(keep):
            nz = np.flatnonzero(counts[j])
            self.store.insert(tuple(coords[i]), ids[j, nz].copy(),
                              counts[j, nz].copy(), labels_l[j],
                              probs_l[j], version)

    # -- labels ---------------------------------------------------------

    def _poll_labels(self) -> bool:
        if self._consumer is None:
            return False
        msgs = self._consumer.poll_batch(512, self.config.poll_timeout_s)
        if not msgs:
            return False
        offsets: dict = {}
        for m in msgs:
            offsets[(m.topic, m.partition)] = max(
                offsets.get((m.topic, m.partition), 0), m.offset + 1)
            rec = parse_label(m.value)
            if rec is None:
                self.store.count_malformed()
            else:
                self.store.join(rec.key, rec.label)
        with self._lock:
            self._labels_polled += len(msgs)
        try:
            self._consumer.commit_offsets(offsets)
        except Exception as e:  # noqa: BLE001 — at-least-once: re-polls rejoin
            log.info("feedback commit failed (labels will replay): %s", e)
        return True

    # -- retraining -----------------------------------------------------

    def _trigger(self) -> Optional[str]:
        cfg = self.config
        snap = self.store.snapshot()
        with self._lock:
            joined_before = self._joined_at_last_retrain
            last_at = self._last_retrain_clock
            in_flight = self._in_flight
            # One candidate in flight: a published version that has not
            # been judged yet (promote/reject) blocks further retrains —
            # stacking candidates would race the shadow evidence.
            outstanding = (self._published - self._promoted
                           - self._rejected)
        if in_flight or outstanding > 0:
            return None
        if last_at is not None and self._clock() - last_at < cfg.cooldown_s:
            return None
        if snap["labeled"] < cfg.min_labeled:
            return None
        new_labels = snap["joined"] - joined_before
        if new_labels < cfg.min_new_labels:
            return None
        # Drift is judged on rows the ACTIVE model scored: a just-promoted
        # fix must not re-trigger off its predecessor's stale errors.
        labeled, errors = self.store.error_stats(
            last_n=cfg.error_window,
            version=getattr(self._hotswap, "active_version", None))
        if labeled and errors / labeled > cfg.error_threshold:
            return "drift"
        if cfg.rows_trigger is not None and new_labels >= cfg.rows_trigger:
            return "rows"
        if cfg.interval_s is not None and (
                last_at is None or self._clock() - last_at >= cfg.interval_s):
            return "interval"
        return None

    def _maybe_retrain(self) -> bool:
        reason = self._trigger()
        if reason is None:
            return False
        now_v = self._now()
        with self._lock:
            self._triggered += 1
            self._in_flight = True
            self._last_trigger = reason
            if self._first_trigger_at is None:
                self._first_trigger_at = now_v
        try:
            self._retrain(reason)
        except Exception as e:  # noqa: BLE001 — a failed retrain is counted
            with self._lock:
                self._failed += 1
            log.warning("windowed retrain failed: %s", e, exc_info=True)
        finally:
            snap = self.store.snapshot()
            with self._lock:
                self._in_flight = False
                self._last_retrain_clock = self._clock()
                self._joined_at_last_retrain = snap["joined"]
        return True

    def _retrain(self, reason: str) -> None:
        from fraud_detection_tpu.models.train_trees import (
            refresh_gradient_boosting)
        from fraud_detection_tpu.models.trees import TreeEnsemble

        cfg = self.config
        rows = self.store.labeled_rows()[-cfg.max_train_rows:]
        feat = self._featurizer_now()
        active = getattr(self._hotswap, "active_pipeline", self._hotswap)
        model = getattr(active, "model", None)
        if not isinstance(model, TreeEnsemble) or model.kind != "xgboost":
            raise RuntimeError(
                f"learn loop refreshes xgboost ensembles; active model is "
                f"{type(model).__name__}"
                f"{'/' + model.kind if isinstance(model, TreeEnsemble) else ''}"
                " — serve an xgboost registry model to close the loop")
        if self._base_model is None:
            self._base_model = model   # the original, pre-growth ensemble
        base = model
        if model.num_trees + cfg.refresh_rounds > cfg.max_trees:
            # Bounded growth: past the cap, warm-start from the ORIGINAL
            # base — the window carries the recent signal either way.
            base = self._base_model
        X, y = self._densify(rows, feat)
        t0 = time.perf_counter()
        refreshed, info = refresh_gradient_boosting(
            base, X, y, n_rounds=cfg.refresh_rounds)
        wall = time.perf_counter() - t0
        # Validation on the window itself: does the candidate actually
        # agree with the ground truth the primary got wrong?
        from fraud_detection_tpu.models import trees as trees_mod

        n = len(rows)
        proba = np.asarray(trees_mod.predict_proba(
            refreshed, np.asarray(X[:n], np.float32)))
        cand_err = float(np.mean((proba[:, 1] > 0.5) != (y[:n] > 0.5)))
        prim_err = float(np.mean(
            [r.pred_label != r.label for r in rows]))
        active_version = getattr(self._hotswap, "active_version", None)
        mv = self._registry.publish(
            feat, refreshed,
            parent=active_version,
            metrics={"window_error_rate_primary": round(prim_err, 6),
                     "window_error_rate_candidate": round(cand_err, 6),
                     "window_rows": n},
            extra={"learn": {**info, "trigger": reason,
                             "triggered_at_s": self._now(),
                             "warm_started_from": active_version,
                             "retrain_wall_s": round(wall, 3)}})
        with self._lock:
            self._published += 1
            self._published_versions.append(mv.version)
            self._last_retrain_wall = wall
            self._retrain_wall_total += wall
            self._candidate_error = cand_err
            self._primary_error = prim_err
        log.info("learn: published v%04d (%s trigger, %d rows, "
                 "primary err %.3f -> candidate err %.3f, %.2fs)",
                 mv.version, reason, n, prim_err, cand_err, wall)

    @staticmethod
    def _densify(rows, feat) -> Tuple[np.ndarray, np.ndarray]:
        """Labeled window -> dense (N, F) TF-IDF matrix + labels, exactly
        the feature semantics the serving traversal reads (count * idf)."""
        f = int(feat.num_features)
        idf = np.asarray(feat.idf_array(), np.float32)
        X = np.zeros((len(rows), f), np.float32)
        for i, r in enumerate(rows):
            ids = np.asarray(r.ids, np.int64)
            X[i, ids] = np.asarray(r.counts, np.float32) * idf[ids]
        y = np.asarray([r.label for r in rows], np.float32)
        return X, y

    # -- shadow replay --------------------------------------------------

    def on_transition(self, record: dict) -> None:
        """LifecycleController observer: track our candidates' fates.
        Runs on the watcher thread — cheap bookkeeping only; the heavy
        replay happens on the lane."""
        event = record.get("event")
        version = record.get("version")
        with self._lock:
            ours = version in self._published_versions
            if event == "stage" and ours and self.config.replay_shadow:
                self._replay_pending = version
            elif event == "promote" and ours:
                self._promoted += 1
                self._promoted_versions.append(version)
                if self._promoted_at is None:
                    self._promoted_at = self._now()
            elif event == "reject" and ours:
                self._rejected += 1
            elif event == "rollback":
                self._replay_pending = None

    def _maybe_replay(self) -> bool:
        with self._lock:
            version = self._replay_pending
            if version is None or version in self._replayed:
                self._replay_pending = None
                return False
        sh = self._shadow
        if sh is None or sh.candidate_version != version:
            return False
        rows = self.store.labeled_rows()
        if not rows:
            return False
        rows = rows[-self.config.replay_rows:]
        for start in range(0, len(rows), 256):
            chunk = rows[start : start + 256]
            width = max(1, max(len(r.ids) for r in chunk))
            ids = np.zeros((len(chunk), width), chunk[0].ids.dtype)
            counts = np.zeros((len(chunk), width), np.uint16)
            for i, r in enumerate(chunk):
                ids[i, : len(r.ids)] = r.ids
                counts[i, : len(r.counts)] = r.counts
            sh.submit_encoded(ids, counts,
                              np.asarray([r.pred_label for r in chunk],
                                         np.int32),
                              np.asarray([r.prob for r in chunk],
                                         np.float64))
        with self._lock:
            self._replayed.add(version)
            self._replay_pending = None
        return True

    # -- regression rollback -------------------------------------------

    def _maybe_rollback(self) -> None:
        cfg = self.config
        if cfg.rollback_error_rate is None or self._controller is None:
            return
        with self._lock:
            if not self._promoted_versions:
                return
            version = self._promoted_versions[-1]
            if version in self._rollback_done:
                return
        if getattr(self._hotswap, "active_version", None) != version:
            return
        stats = self.store.error_by_version().get(str(version))
        if stats is None or stats["labeled"] < cfg.rollback_min_labeled:
            return
        if stats["error_rate"] <= cfg.rollback_error_rate:
            return
        parent = None
        try:
            parent = self._registry.get(version).manifest.get("parent")
        except Exception:  # noqa: BLE001
            pass
        if parent is None:
            return
        from fraud_detection_tpu.utils.racecheck import RaceError

        try:
            self._controller.rollback(parent)
        except RaceError:
            return  # watcher mid-tick: retry next lane tick
        except Exception as e:  # noqa: BLE001 — audited failure, counted
            log.warning("regression rollback to v%04d failed: %s", parent, e)
            return
        with self._lock:
            self._rolled_back += 1
            self._rollback_done.add(version)
        log.warning("learn: promoted v%04d regressed (label error %.3f "
                    "over %d rows) — rolled back to v%04d",
                    version, stats["error_rate"], stats["labeled"], parent)

    # ------------------------------------------------------------------
    # observability / teardown
    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``learn`` block of engine ``health()`` (LEARN_BLOCK_SCHEMA,
        FC301-checked)."""
        window = self.store.snapshot()
        labeled, errors = self.store.error_stats(
            last_n=self.config.error_window)
        with self._lock:
            snap = {
                "window": window,
                "queue_depth": self._queue.qsize(),
                "submitted": self._submitted,
                "dropped": self._dropped,
                "sampled_out": self._sampled_out,
                "encode_errors": self._encode_errors,
                "labels_polled": self._labels_polled,
                "triggered": self._triggered,
                "published": self._published,
                "failed": self._failed,
                "in_flight": self._in_flight,
                "promoted": self._promoted,
                "rejected": self._rejected,
                "rolled_back": self._rolled_back,
                "published_versions": list(self._published_versions),
                "last_trigger": self._last_trigger,
                "first_trigger_at_s": self._first_trigger_at,
                "promoted_at_s": self._promoted_at,
                "last_retrain_wall_s": (
                    round(self._last_retrain_wall, 3)
                    if self._last_retrain_wall is not None else None),
                "retrain_wall_s_total": round(self._retrain_wall_total, 3),
                "recent_error_rate": (round(errors / labeled, 6)
                                      if labeled else None),
                "primary_window_error_rate": (
                    round(self._primary_error, 6)
                    if self._primary_error is not None else None),
                "candidate_window_error_rate": (
                    round(self._candidate_error, 6)
                    if self._candidate_error is not None else None),
                "error_by_version": self.store.error_by_version(),
            }
        return snap

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait until the scored-batch queue is empty (tests/teardown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:
                return True
            time.sleep(0.01)
        return self._queue.unfinished_tasks == 0

    def close(self, timeout: float = 30.0) -> bool:
        """Drain (bounded) then stop the lane thread."""
        drained = self.drain(timeout)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(timeout, 30.0))
            return drained and not self._thread.is_alive()
        return drained
