"""Bounded keyed sliding-window store of recently scored rows.

The learn loop's state between a row being scored and its ground-truth
label arriving (minutes to hours later in production; virtual seconds in a
game day). Each entry is keyed by the row's SOURCE COORDINATE
(topic, partition, offset — the same key DLQ records and feedback labels
carry) and retains the row's PACKED ENCODED FEATURES (the featurizer's
sparse (ids, counts) arrays, a few hundred bytes/row), the primary model's
prediction, and which model version scored it. Raw text is NEVER retained:
the window is a training buffer, not a transcript log, and the packed form
is both smaller and exactly what the tree trainer consumes.

Bounds are explicit and accounted:

* ``capacity`` — beyond it the OLDEST row is evicted (insertion order);
* ``max_age_s`` — rows older than this are swept on insert and on demand.

Eviction is never silent: the store remembers, per source partition, the
highest offset it has ever evicted, so a label arriving for a gone row is
classified ``expired`` (we HAD it, the window moved on) while a label for a
row this store never held goes to a BOUNDED pending buffer — the join is
symmetric stream-stream buffering, because a label can legitimately race
its row (at-least-once replays; warp-mode scenarios where virtual label
delay collapses below scoring latency). A pending label resolves to
``joined`` the moment its row inserts, or falls to ``missed`` when it
overflows the buffer or out-ages ``max_age_s``. The accounting invariant —
the hypothesis property tests/test_learn.py pins —

    joined + expired + missed + pending == labels_seen

holds across any interleaving of inserts, joins, and evictions (at
quiescence pending drains to zero, recovering the three-term form);
malformed feedback records are counted separately (they carry no
coordinate to classify). All surfaces are thread-safe (one small lock):
the learn-lane worker inserts/joins, health pollers snapshot.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

Coordinate = Tuple[str, int, int]


@dataclass
class StoredRow:
    """One scored row awaiting (or holding) its ground-truth label."""

    key: Coordinate
    ids: np.ndarray          # (L,) int16/int32 hashed feature ids (packed)
    counts: np.ndarray       # (L,) uint16 term counts
    pred_label: int          # the primary model's prediction at scoring time
    prob: float              # the primary model's p(class=1)
    version: Optional[int]   # active model version that scored it
    inserted_at: float       # store-clock seconds
    label: Optional[int] = None   # ground truth once joined


class WindowStore:
    """See module docstring. ``clock`` is injectable (tests and the
    scenario harness drive virtual seconds)."""

    def __init__(self, capacity: int = 8192, *, max_age_s: float = 3600.0,
                 clock: Callable[[], float] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if max_age_s <= 0:
            raise ValueError(f"max_age_s must be > 0, got {max_age_s}")
        import time

        self.capacity = capacity
        self.max_age_s = max_age_s
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._rows: "OrderedDict[Coordinate, StoredRow]" = OrderedDict()
        # (topic, partition) -> highest offset ever EVICTED from this store:
        # the expired-vs-missed classifier for late labels.
        self._evicted_watermark: Dict[Tuple[str, int], int] = {}
        # Labels that arrived BEFORE their row (symmetric join buffer):
        # key -> (label, stamped_at). Bounded by the same capacity/age as
        # the row window; overflow/age-out counts as missed.
        self._pending_labels: "OrderedDict[Coordinate, Tuple[int, float]]" \
            = OrderedDict()
        self._labeled = 0
        self._evicted = 0
        self._evicted_labeled = 0
        self._inserted = 0
        # Label accounting (the invariant: joined+expired+missed==seen).
        self._labels_seen = 0
        self._joined = 0
        self._expired = 0
        self._missed = 0
        self._malformed = 0

    # ------------------------------------------------------------------
    # rows (learn-lane writer)
    # ------------------------------------------------------------------

    def insert(self, key: Coordinate, ids: np.ndarray, counts: np.ndarray,
               pred_label: int, prob: float,
               version: Optional[int] = None) -> None:
        """Insert one scored row (idempotent per coordinate: a replayed
        at-least-once duplicate overwrites in place, keeping its slot's
        age — the window never double-counts a source row)."""
        now = self._clock()
        row = StoredRow(key, ids, counts, int(pred_label), float(prob),
                        version, now)
        with self._lock:
            prior = self._rows.pop(key, None)
            if prior is not None:
                if prior.label is not None and row.label is None:
                    # A duplicate delivery must not un-join a labeled row.
                    row.label = prior.label
                    row.inserted_at = prior.inserted_at
                elif prior.label is None:
                    row.inserted_at = prior.inserted_at
                if prior.label is not None:
                    self._labeled -= 1
            early = self._pending_labels.pop(key, None)
            if early is not None:
                # The label raced its row (pending buffer): join NOW.
                # Every buffered label is accounted exactly once.
                row.label = early[0]
                self._joined += 1
            self._rows[key] = row
            if row.label is not None:
                self._labeled += 1
            self._inserted += 1
            self._sweep_locked(now)

    def _evict_locked(self, key: Coordinate, row: StoredRow) -> None:
        wm_key = (key[0], key[1])
        prior = self._evicted_watermark.get(wm_key, -1)
        self._evicted_watermark[wm_key] = max(prior, key[2])
        self._evicted += 1
        if row.label is not None:
            self._labeled -= 1
            self._evicted_labeled += 1

    def _sweep_locked(self, now: float) -> None:
        while len(self._rows) > self.capacity:
            key, row = self._rows.popitem(last=False)
            self._evict_locked(key, row)
        cutoff = now - self.max_age_s
        while self._rows:
            key = next(iter(self._rows))
            row = self._rows[key]
            if row.inserted_at >= cutoff:
                break
            del self._rows[key]
            self._evict_locked(key, row)
        # Pending (row-less) labels: overflow and age-out fall to missed —
        # bounded by the same capacity/age discipline as the row window.
        while len(self._pending_labels) > self.capacity:
            self._pending_labels.popitem(last=False)
            self._missed += 1
        while self._pending_labels:
            key = next(iter(self._pending_labels))
            if self._pending_labels[key][1] >= cutoff:
                break
            del self._pending_labels[key]
            self._missed += 1

    def sweep(self) -> None:
        """Age-based eviction on demand (the loop calls it per tick so an
        idle stream still expires its window)."""
        with self._lock:
            self._sweep_locked(self._clock())

    # ------------------------------------------------------------------
    # labels (learn-lane writer)
    # ------------------------------------------------------------------

    def join(self, key: Coordinate, label: int) -> str:
        """Join one ground-truth label; returns its fate —
        ``"joined"`` | ``"expired"`` | ``"pending"``. A second label for a
        still-held row overwrites (latest verdict wins) and counts as
        joined: the invariant counts LABELS, not rows. A label whose row
        is neither held nor known-evicted buffers as PENDING (it may have
        raced its row — see module docstring) and later resolves to
        joined (row arrives) or missed (overflow/age-out)."""
        with self._lock:
            self._labels_seen += 1
            row = self._rows.get(key)
            if row is not None:
                if row.label is None:
                    self._labeled += 1
                row.label = int(label)
                self._joined += 1
                return "joined"
            wm = self._evicted_watermark.get((key[0], key[1]), -1)
            if key[2] <= wm:
                self._expired += 1
                return "expired"
            if key in self._pending_labels:
                # Duplicate early label: the superseded one is accounted
                # as missed (exactly one pending slot per coordinate).
                self._missed += 1
            self._pending_labels[key] = (int(label), self._clock())
            self._pending_labels.move_to_end(key)
            self._sweep_locked(self._clock())
            return "pending"

    def count_malformed(self) -> None:
        """One undecodable feedback record (no coordinate to classify)."""
        with self._lock:
            self._malformed += 1

    # ------------------------------------------------------------------
    # training window (learn-lane reader)
    # ------------------------------------------------------------------

    def labeled_rows(self) -> List[StoredRow]:
        """Snapshot copy of every labeled row, oldest first (the retrain
        input; entries are not removed — the window keeps sliding)."""
        with self._lock:
            return [r for r in self._rows.values() if r.label is not None]

    def error_stats(self, last_n: Optional[int] = None,
                    version: Optional[int] = None) -> Tuple[int, int]:
        """(labeled, errors) over the labeled window — ``errors`` counts
        rows whose stored prediction disagrees with the joined ground
        truth. ``last_n`` restricts to the most recently INSERTED labeled
        rows; ``version`` restricts to rows SCORED BY that model version
        (the drift trigger judges the ACTIVE model, so a just-promoted
        fix isn't re-triggered by its predecessor's stale errors)."""
        with self._lock:
            rows = [r for r in self._rows.values() if r.label is not None]
        if version is not None:
            rows = [r for r in rows if r.version == version]
        if last_n is not None:
            rows = rows[-last_n:]
        errors = sum(1 for r in rows if r.pred_label != r.label)
        return len(rows), errors

    def error_by_version(self) -> Dict[str, dict]:
        """Labeled/error counts segmented by the model version that scored
        each row — the promotion-recovery evidence (a promoted candidate's
        rows should stop erring)."""
        with self._lock:
            rows = [r for r in self._rows.values() if r.label is not None]
        out: Dict[str, dict] = {}
        for r in rows:
            k = str(r.version)
            slot = out.setdefault(k, {"labeled": 0, "errors": 0})
            slot["labeled"] += 1
            slot["errors"] += int(r.pred_label != r.label)
        for slot in out.values():
            slot["error_rate"] = round(slot["errors"] / slot["labeled"], 6)
        return out

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rows": len(self._rows),
                "labeled": self._labeled,
                "capacity": self.capacity,
                "inserted": self._inserted,
                "evicted": self._evicted,
                "evicted_labeled": self._evicted_labeled,
                "labels_seen": self._labels_seen,
                "joined": self._joined,
                "expired": self._expired,
                "missed": self._missed,
                "pending_labels": len(self._pending_labels),
                "malformed_labels": self._malformed,
                "accounting_exact": (
                    self._joined + self._expired + self._missed
                    + len(self._pending_labels) == self._labels_seen),
            }
