from fraud_detection_tpu.models.linear import LogisticRegression
from fraud_detection_tpu.models.pipeline import ServingPipeline

__all__ = ["LogisticRegression", "ServingPipeline"]
