from fraud_detection_tpu.models.linear import LogisticRegression
from fraud_detection_tpu.models.pipeline import ServingPipeline

__all__ = ["LogisticRegression", "ServingPipeline"]

# Trainers import lazily where used (models.train_linear / train_trees /
# train_llm) — importing them here would pull optax into every serve-path
# process.
