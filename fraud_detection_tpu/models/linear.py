"""Logistic-regression scorer, TPU-native.

Replaces Spark's ``LogisticRegressionModel.transform`` (the final stage of the
shipped serving pipeline, dialogue_classification_model/stages/4_LogisticRegression_*)
with two jitted paths:

  * dense:  margin = X @ w + b over a (B, F) TF-IDF matrix — one MXU matvec.
  * sparse fused: for hashed-TF rows the margin is a gather + segment-sum over
    the padded EncodedBatch — features are never materialized. ``idf * w`` is
    folded into one effective weight vector at model-build time, so serve-time
    work per token is a single gather-accumulate. This is the fast path that
    replaces the reference's per-row 5-stage Spark job (utils/agent_api.py:139-158).

Spark semantics replicated: rawPrediction = [-m, m], probability = sigmoid(m),
prediction = 1 iff p > threshold (threshold 0.5 in the shipped artifact).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fraud_detection_tpu.featurize.tfidf import EncodedBatch


@jax.tree_util.register_dataclass
@dataclass
class LogisticRegression:
    """Binary logistic regression parameters as a jax pytree.

    ``weights`` are in *feature* space (post-IDF). For fused sparse scoring over
    hashed term counts, use ``effective_weights = idf * weights`` (precomputed
    via ``fold_idf``).
    """

    weights: jax.Array            # (F,) float32
    intercept: jax.Array          # () float32
    threshold: float = 0.5

    @classmethod
    def from_arrays(cls, weights, intercept, threshold: float = 0.5) -> "LogisticRegression":
        return cls(
            weights=jnp.asarray(np.asarray(weights, np.float32)),
            intercept=jnp.asarray(np.float32(intercept)),
            threshold=float(threshold),
        )

    def fold_idf(self, idf) -> "LogisticRegression":
        """Fold an IDF vector into the weights (for raw term-count inputs)."""
        return LogisticRegression(
            weights=self.weights * jnp.asarray(idf, self.weights.dtype),
            intercept=self.intercept,
            threshold=self.threshold,
        )


def margin_dense(model: LogisticRegression, x: jax.Array) -> jax.Array:
    """(B, F) dense features -> (B,) raw margin."""
    return x @ model.weights + model.intercept


# ---------------------------------------------------------------------------
# Packed-buffer serving entries (models/pipeline.py device-resident hot path):
# the host stacks an EncodedBatch's int16 ids and uint16 counts into ONE
# (B, 2, L) int16 staging array, so a micro-batch costs exactly one
# host->device transfer; the program unpacks on-device (a reshape + bitcast,
# free next to the gather). Each entry has a donating twin — when the
# platform consumes donated buffers (models/pipeline.py donation_effective),
# the per-batch input buffer is handed to XLA at dispatch instead of waiting
# for Python refcounting to release it.
# ---------------------------------------------------------------------------


def unpack_rows(packed: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(B, 2, L) int16 -> (ids int32 (B, L), counts float32 (B, L)).

    counts travel as uint16 bits inside the int16 container; the bitcast
    restores them exactly (values up to 65535, matching EncodedBatch)."""
    ids = packed[:, 0, :].astype(jnp.int32)
    counts = jax.lax.bitcast_convert_type(packed[:, 1, :], jnp.uint16)
    return ids, counts.astype(jnp.float32)


def _prob_packed_impl(model: LogisticRegression, packed: jax.Array):
    ids, counts = unpack_rows(packed)
    gathered = model.weights[ids]                       # (B, L)
    m = jnp.sum(gathered * counts, axis=-1) + model.intercept
    return jax.nn.sigmoid(m)


_prob_packed = jax.jit(_prob_packed_impl)
_prob_packed_donated = jax.jit(_prob_packed_impl, donate_argnums=(1,))


def prob_packed(model: LogisticRegression, packed: jax.Array,
                donate: bool = False) -> jax.Array:
    """Packed-buffer variant of ``prob_encoded_arrays`` (idf must be folded
    into the weights). ``donate=True`` dispatches through the donating
    program — the caller must not touch ``packed`` afterwards."""
    fn = _prob_packed_donated if donate else _prob_packed
    return fn(model, packed)


# ---------------------------------------------------------------------------
# int8 scoring variant: symmetric per-BLOCK weight quantization. The gather
# reads int8 codes (a quarter of the fp32 weight bytes out of HBM) plus one
# f32 scale per 128-weight block; per-block scales matter because TF-IDF LR
# weights carry a few huge outliers — a single per-tensor scale quantized
# everything else to mush (max |Δp| ~0.38 on the shipped artifact; blocks
# bring it under ~1e-2). Quantization error comes from the one weight
# rounding, nothing else; fp32 parity is pinned in tests/test_device_path.py.
# ---------------------------------------------------------------------------

_Q8_BLOCK = 128


def quantize_weights(model: LogisticRegression,
                     block: int = _Q8_BLOCK) -> tuple[jax.Array, jax.Array]:
    """(int8 codes (ceil(F/block)*block,), f32 per-block scales (nb,)) with
    w[i] ~= scales[i // block] * w_q[i]. Codes stay padded to a whole number
    of blocks so consumers recover ``block`` from the two shapes."""
    w = model.weights
    f = w.shape[0]
    nb = -(-f // block)
    wp = jnp.pad(w, (0, nb * block - f)).reshape(nb, block)
    absmax = jnp.maximum(jnp.max(jnp.abs(wp), axis=1), 1e-12)
    scales = (absmax / 127.0).astype(jnp.float32)
    w_q = jnp.clip(jnp.round(wp / scales[:, None]),
                   -127, 127).astype(jnp.int8).reshape(-1)
    return w_q, scales


def _prob_packed_q8_impl(w_q: jax.Array, scales: jax.Array,
                         intercept: jax.Array, packed: jax.Array):
    block = w_q.shape[0] // scales.shape[0]     # static under jit
    ids = packed[:, 0, :].astype(jnp.int32)
    counts = jax.lax.bitcast_convert_type(packed[:, 1, :], jnp.uint16)
    per_term = (w_q[ids].astype(jnp.float32) * scales[ids // block]
                * counts.astype(jnp.float32))
    return jax.nn.sigmoid(jnp.sum(per_term, axis=-1) + intercept)


_prob_packed_q8 = jax.jit(_prob_packed_q8_impl)
_prob_packed_q8_donated = jax.jit(_prob_packed_q8_impl, donate_argnums=(3,))


def prob_packed_q8(w_q: jax.Array, scales: jax.Array, intercept: jax.Array,
                   packed: jax.Array, donate: bool = False) -> jax.Array:
    """int8 packed-buffer scoring (see ``quantize_weights``)."""
    fn = _prob_packed_q8_donated if donate else _prob_packed_q8
    return fn(w_q, scales, intercept, packed)


def margin_encoded(model: LogisticRegression, ids: jax.Array, counts: jax.Array) -> jax.Array:
    """Fused sparse scoring over padded (B, L) bucket ids / counts.

    ``model.weights`` must already include the IDF factor (see ``fold_idf``);
    padding rows have count 0 so they contribute nothing.
    """
    gathered = model.weights[ids.astype(jnp.int32)]          # (B, L)
    return jnp.sum(gathered * counts.astype(model.weights.dtype),
                   axis=-1) + model.intercept


@partial(jax.jit, static_argnames=())
def _predict_dense(model: LogisticRegression, x: jax.Array):
    m = margin_dense(model, x)
    p = jax.nn.sigmoid(m)
    return (p > model.threshold).astype(jnp.int32), p


@jax.jit
def _predict_encoded(model: LogisticRegression, ids: jax.Array, counts: jax.Array):
    m = margin_encoded(model, ids, counts)
    p = jax.nn.sigmoid(m)
    return (p > model.threshold).astype(jnp.int32), p


@jax.jit
def _prob_encoded(model: LogisticRegression, ids: jax.Array, counts: jax.Array):
    return jax.nn.sigmoid(margin_encoded(model, ids, counts))


def prob_encoded(model: LogisticRegression, batch: EncodedBatch) -> jax.Array:
    """Single-output serving path: (B,) p(class=1) only.

    Fetching one array instead of (labels, probs) halves device->host
    round-trips; labels are derived on the host with the identical
    ``p > threshold`` comparison (thresholding commutes with the fetch)."""
    return _prob_encoded(model, jnp.asarray(batch.ids), jnp.asarray(batch.counts))


def predict_dense(model: LogisticRegression, x) -> tuple[jax.Array, jax.Array]:
    """Dense path: returns (predictions int32 (B,), probability of class 1 (B,))."""
    return _predict_dense(model, jnp.asarray(x))


def predict_encoded(model: LogisticRegression, batch: EncodedBatch) -> tuple[jax.Array, jax.Array]:
    """Fused sparse path over an EncodedBatch (idf must be folded into weights)."""
    return _predict_encoded(model, jnp.asarray(batch.ids), jnp.asarray(batch.counts))


def prob_encoded_arrays(model: LogisticRegression, ids: jax.Array,
                        counts: jax.Array) -> jax.Array:
    """Device-array variant of ``prob_encoded`` for callers that place the
    encoded rows themselves (e.g. the mesh-backed ServingPipeline, which
    row-shards them first — jit follows the input shardings, so the same
    compiled program serves single-chip and data-parallel)."""
    return _prob_encoded(model, ids, counts)


def predict_encoded_mesh(model: LogisticRegression, batch: EncodedBatch,
                         mesh) -> tuple[np.ndarray, np.ndarray]:
    """Data-parallel serving over a device mesh: the encoded batch's rows are
    sharded on the mesh "data" axis (weights replicated), each device scores
    its shard with the same fused gather-accumulate as ``prob_encoded``, and
    ONE gather returns the full probability vector — the horizontal-serving
    shape of BASELINE's v5e-8 north star (N chips scoring one micro-batch;
    the reference scales the same way with N Spark consumers on its
    3-partition topic). Rows are zero-padded to a data-axis multiple on the
    host; padded rows cost sigmoid(intercept) each and are sliced off before
    returning. Returns host (pred, prob) at the real row count."""
    from fraud_detection_tpu.parallel.mesh import shard_rows

    n = batch.ids.shape[0]
    ids = shard_rows(np.asarray(batch.ids), mesh)
    counts = shard_rows(np.asarray(batch.counts), mesh)
    prob = np.asarray(_prob_encoded(model, ids, counts))[:n]
    return (prob > model.threshold).astype(np.int32), prob
