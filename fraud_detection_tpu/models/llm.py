"""On-pod explanation LLM: a TPU-native decoder-only transformer.

BASELINE.json config 5 asks for the DeepSeek HTTPS round-trip
(/root/reference/utils/agent_api.py:36,66) to be replaceable by a model served
from the same pod as the classifier. This module is that model: a standard
pre-norm decoder (RMSNorm / RoPE multi-head attention / SwiGLU), written as
pure-functional JAX over a params pytree so the same forward runs

  * single-chip (tests, small models) — long sequences dispatch to the
    Pallas flash-attention kernel (``ops/attention.py``: blockwise online
    softmax, O(T·d) memory, both matmuls on the MXU),
  * tensor-parallel over a mesh "model" axis — head-sharded attention and
    hidden-sharded MLP with GSPMD inserting the all-reduces (the Megatron
    column/row-parallel layout expressed as shardings, not explicit
    collectives), and
  * sequence-parallel for long transcripts via **ring attention**
    (``ring_attention``): each device holds a sequence shard, K/V blocks
    rotate around the ring with ``ppermute`` while a flash-style online
    softmax accumulates — exact attention, memory O(T/n) per chip, ICI
    traffic fully overlapped block math.

The byte-level tokenizer keeps the model self-contained (no vocab downloads,
zero egress); real pretrained weights convert into this exact pytree layout
via ``checkpoint/hf_convert.py`` (HF safetensors -> Params, incl. GQA/MQA,
untied heads, and Gemma's norm/scale/GeGLU quirks — verified against an
independent numpy forward in tests/test_hf_convert.py).
``LanguageModel.generate_text`` plugs into the explanation layer through
``explain.onpod.OnPodBackend.from_model`` /
``OnPodBackend.from_hf_checkpoint``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "model"
SEQ_AXIS = "seq"
DATA_AXIS = "data"  # batch axis on 2-D (data, seq) / (data, model) meshes

Params = Dict[str, jax.Array]


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 258          # 256 bytes + BOS + EOS
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 1024
    max_seq: int = 2048
    rope_theta: float = 10000.0
    dtype: jnp.dtype = jnp.float32  # bfloat16 on real TPU runs
    # --- pretrained-checkpoint surface (checkpoint/hf_convert.py) ---
    n_kv_heads: Optional[int] = None   # < n_heads = GQA; 1 = MQA (Gemma-2B)
    head_dim_override: Optional[int] = None  # Gemma: head_dim != D/H
    activation: str = "silu"           # "silu" | "gelu" (Gemma's GeGLU tanh)
    embed_scale: float = 1.0           # Gemma scales embeddings by sqrt(D)
    tie_embeddings: bool = True        # False = separate "lm_head" param
    rms_eps: float = 1e-6

    @property
    def head_dim(self) -> int:
        return (self.head_dim_override if self.head_dim_override is not None
                else self.d_model // self.n_heads)

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    BOS: int = field(default=256, init=False)
    EOS: int = field(default=257, init=False)


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: TransformerConfig) -> Params:
    """Random-init parameter pytree. Layout (per layer l):
    wq (D, H, d), wk/wv (D, Hkv, d), wo (H, d, D), w_gate/w_up (D, F),
    w_down (F, D), ln1/ln2 (D,), plus embed (V, D) and ln_f (D,). The output
    head ties embed unless cfg.tie_embeddings=False adds "lm_head" (V, D)."""
    keys = jax.random.split(rng, cfg.n_layers * 7 + 2)
    scale = 1.0 / math.sqrt(cfg.d_model)
    p: Params = {
        "embed": (jax.random.normal(keys[0], (cfg.vocab_size, cfg.d_model)) * scale
                  ).astype(cfg.dtype)}
    if not cfg.tie_embeddings:
        p["lm_head"] = (jax.random.normal(
            keys[-1], (cfg.vocab_size, cfg.d_model)) * scale).astype(cfg.dtype)
    h, hkv, d = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    for l in range(cfg.n_layers):
        k = keys[1 + l * 7 : 1 + (l + 1) * 7]
        p[f"l{l}.wq"] = (jax.random.normal(k[0], (cfg.d_model, h, d)) * scale).astype(cfg.dtype)
        p[f"l{l}.wk"] = (jax.random.normal(k[1], (cfg.d_model, hkv, d)) * scale).astype(cfg.dtype)
        p[f"l{l}.wv"] = (jax.random.normal(k[2], (cfg.d_model, hkv, d)) * scale).astype(cfg.dtype)
        p[f"l{l}.wo"] = (jax.random.normal(k[3], (h, d, cfg.d_model)) * scale).astype(cfg.dtype)
        p[f"l{l}.w_gate"] = (jax.random.normal(k[4], (cfg.d_model, cfg.d_ff)) * scale).astype(cfg.dtype)
        p[f"l{l}.w_up"] = (jax.random.normal(k[5], (cfg.d_model, cfg.d_ff)) * scale).astype(cfg.dtype)
        p[f"l{l}.w_down"] = (jax.random.normal(k[6], (cfg.d_ff, cfg.d_model)) * scale).astype(cfg.dtype)
        p[f"l{l}.ln1"] = jnp.ones(cfg.d_model, cfg.dtype)
        p[f"l{l}.ln2"] = jnp.ones(cfg.d_model, cfg.dtype)
    p["ln_f"] = jnp.ones(cfg.d_model, cfg.dtype)
    return p


def param_shardings(cfg: TransformerConfig, mesh: Mesh) -> Dict[str, NamedSharding]:
    """Megatron TP layout as shardings: attention sharded over heads, MLP over
    the hidden dim; norms/embeddings replicated. GSPMD derives the matching
    activation collectives (all-reduce after row-parallel wo / w_down)."""
    s: Dict[str, NamedSharding] = {}
    rep = NamedSharding(mesh, P())
    for name in ("embed", "ln_f"):
        s[name] = rep
    if not cfg.tie_embeddings:
        s["lm_head"] = rep
    # GQA: when the kv-head count doesn't divide over the model axis (MQA has
    # a single kv head), replicate k/v — the Megatron convention.
    kv_spec = (P(None, MODEL_AXIS, None)
               if cfg.kv_heads % mesh.shape[MODEL_AXIS] == 0 else P())
    for l in range(cfg.n_layers):
        s[f"l{l}.wq"] = NamedSharding(mesh, P(None, MODEL_AXIS, None))
        s[f"l{l}.wk"] = NamedSharding(mesh, kv_spec)
        s[f"l{l}.wv"] = NamedSharding(mesh, kv_spec)
        s[f"l{l}.wo"] = NamedSharding(mesh, P(MODEL_AXIS, None, None))
        s[f"l{l}.w_gate"] = NamedSharding(mesh, P(None, MODEL_AXIS))
        s[f"l{l}.w_up"] = NamedSharding(mesh, P(None, MODEL_AXIS))
        s[f"l{l}.w_down"] = NamedSharding(mesh, P(MODEL_AXIS, None))
        s[f"l{l}.ln1"] = rep
        s[f"l{l}.ln2"] = rep
    return s


def _scale_sharding(weight_sh: NamedSharding, scale_shape) -> NamedSharding:
    """Sharding for a Q8 scale: the weight's spec restricted to the dims the
    scale keeps. Scales carry singleton input dims (quantize_params reduces
    with keepdims), so only the weight's OUTPUT dims can be sharded — e.g.
    wq (D, H, d) @ P(None, model, None) gives its (1, H, d) scale
    P(None, model, None), while wo (H, d, D) @ P(model, None, None) gives
    its (1, 1, D) scale full replication."""
    spec = list(weight_sh.spec) + [None] * (len(scale_shape) - len(weight_sh.spec))
    restricted = tuple(None if scale_shape[i] == 1 else spec[i]
                       for i in range(len(scale_shape)))
    return NamedSharding(weight_sh.mesh, P(*restricted))


def shard_params(params: Params, cfg: TransformerConfig, mesh: Mesh) -> Params:
    """Place params (full-precision OR int8-quantized) on the mesh in the
    Megatron TP layout. Q8 leaves shard componentwise: q follows the
    weight's spec, the per-output-channel scale follows on its non-singleton
    dims (``_scale_sharding``) — quantize-then-shard and shard-then-quantize
    both land on this exact placement."""
    sh = param_shardings(cfg, mesh)
    out: Params = {}
    for k, v in params.items():
        if isinstance(v, Q8):
            out[k] = Q8(q=jax.device_put(v.q, sh[k]),
                        scale=jax.device_put(
                            v.scale, _scale_sharding(sh[k], v.scale.shape)))
        else:
            out[k] = jax.device_put(v, sh[k])
    return out


# ---------------------------------------------------------------------------
# int8 weight-only quantization (decode is weight-streaming bound: bf16
# decode on the 2B model measures ~81-83% of HBM peak at 256-token
# samples, so halving the weight bytes is the one lever that moves
# single-stream tokens/sec — measured 1.77x, 135.7 -> 240.7 tok/s)
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclass
class Q8:
    """Per-output-channel int8 weight: ``w ≈ q * scale``.

    ``scale`` keeps q's rank with singleton input dims. Consumers (``_mm``)
    feed ``q`` to the dot through a bare int8->dtype convert and apply the
    scale to the dot's OUTPUT — constant along every contracted dim, so the
    move is exact, and the HBM read stays int8-wide without relying on XLA
    to fuse an operand-side convert*scale chain."""

    q: jax.Array          # int8, the weight's shape
    scale: jax.Array      # f32, singleton along the weight's INPUT dims


#: weight name suffix -> axes reduced for the absmax (the INPUT dims).
_QUANT_REDUCE_AXES = {
    "wq": (0,), "wk": (0,), "wv": (0,),      # (D, h, d): in = D
    "wo": (0, 1),                            # (h, d, D): in = (h, d)
    "w_gate": (0,), "w_up": (0,),            # (D, F): in = D
    "w_down": (0,),                          # (F, D): in = F
    "embed": (1,), "lm_head": (1,),          # (V, D): per-row (gather + head)
}


def quantize_params(params: Params, *, include_embed: bool = True) -> Params:
    """bf16/f32 params -> weight-only int8 with per-output-channel scales.

    Norm gammas stay full precision (tiny, numerically load-bearing).
    ``include_embed=False`` keeps the embedding/output head unquantized
    (it is ~20% of Gemma-2B's bytes; quantizing it costs ~1/127-per-channel
    relative error on logits too, not just activations)."""
    out: Params = {}
    for name, w in params.items():
        suffix = name.rsplit(".", 1)[-1]
        axes = _QUANT_REDUCE_AXES.get(suffix)
        if axes is None or (suffix in ("embed", "lm_head") and not include_embed):
            out[name] = w
            continue
        # Sharded inputs quantize in place: the elementwise q keeps the
        # weight's sharding, and the keepdims absmax reduction lands the
        # scale exactly on _scale_sharding's layout (reduced input dims
        # become singletons; surviving output dims keep their spec) — GSPMD
        # inserts the cross-shard max where an input dim was sharded.
        wf = jnp.asarray(w).astype(jnp.float32)
        absmax = jnp.max(jnp.abs(wf), axis=axes, keepdims=True)
        scale = jnp.maximum(absmax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
        out[name] = Q8(q=q, scale=scale)
    return out


def quantize_params_host(params: dict, *, include_embed: bool = True,
                         compute_dtype=None) -> dict:
    """``quantize_params`` in host numpy, for quantize-BEFORE-upload loads.

    The device upload is the cold-start floor on a tunneled chip (5GB of
    bf16 at single-digit-to-double-digit MB/s), so an int8 serving config
    wants the weights quantized on the host and HALF the bytes shipped —
    not a bf16 upload followed by on-device ``quantize_params``. Same
    contract as the device version (f32 math, keepdims absmax, round-half-
    even, ±127 clip; both numpy and XLA follow IEEE semantics for these
    ops), pinned by tests/test_llm.py's host-vs-device equality test.

    ``compute_dtype``: the model dtype an after-load ``quantize_params``
    would have seen — weights round-trip through it before quantizing, so
    an f32/f16 checkpoint loaded at bf16 quantizes the same rounded values
    on both paths (checkpoint dtype and model dtype differ routinely; both
    numpy/ml_dtypes and XLA cast round-to-nearest-even).

    Takes and returns numpy leaves ({name: ndarray | Q8-of-ndarray});
    callers upload with Q8-aware device placement (checkpoint/hf_convert.py)
    or ``shard_params``."""
    out: dict = {}
    for name, w in params.items():
        suffix = name.rsplit(".", 1)[-1]
        axes = _QUANT_REDUCE_AXES.get(suffix)
        if axes is None or (suffix in ("embed", "lm_head") and not include_embed):
            out[name] = w
            continue
        wf = np.asarray(w)
        if compute_dtype is not None:
            wf = wf.astype(np.dtype(compute_dtype))
        wf = wf.astype(np.float32)
        absmax = np.max(np.abs(wf), axis=axes, keepdims=True)
        scale = np.maximum(absmax, np.float32(1e-8)) / np.float32(127.0)
        q = np.clip(np.round(wf / scale), -127, 127).astype(np.int8)
        out[name] = Q8(q=q, scale=scale)
    return out


def _mm(sub: str, x: jax.Array, w, dtype) -> jax.Array:
    """Einsum against a possibly-quantized weight. An int8 weight enters the
    dot as a bare int8->dtype convert — the HBM read stays int8-wide — and
    its per-output-channel scale multiplies the dot's OUTPUT instead of the
    operand: mathematically identical (the scale is constant along every
    contracted dim), and it removes any reliance on XLA fusing a
    convert*scale*convert chain into the operand load (an operand-side
    dequant leaves a full-width scaled weight on the critical path whenever
    that fusion declines). Scales keep singleton input dims, so they
    broadcast directly against the output's trailing dims for every layer
    weight; the (V, 1) head layout is handled at the logits call site."""
    if isinstance(w, Q8):
        out = jnp.einsum(sub, x, w.q.astype(dtype))
        return (out * w.scale).astype(dtype)
    return jnp.einsum(sub, x, w)


def _embed_rows(emb, tokens: jax.Array, dtype) -> jax.Array:
    """Embedding gather, dequantizing only the gathered rows when int8."""
    if isinstance(emb, Q8):
        return (emb.q[tokens].astype(jnp.float32)
                * emb.scale[tokens]).astype(dtype)
    return emb[tokens].astype(dtype)


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, gamma: jax.Array, eps: float = 1e-6) -> jax.Array:
    """Plain RMSNorm. Gemma's (1 + w) convention is folded into gamma at
    checkpoint-conversion time (checkpoint/hf_convert.py), not special-cased
    here."""
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * gamma


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: (..., T, H, d); positions: (..., T)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # (..., T, d/2)
    cos = jnp.cos(angles)[..., :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[..., :, None, :].astype(x.dtype)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape)


def _attend(q, k, v, mask) -> jax.Array:
    """Plain masked attention. q: (B,T,H,d), k/v: (B,S,H,d), mask (T,S)
    shared across the batch or (B,T,S) per-row (batched decode with uneven
    prompt lengths)."""
    scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
    scores = scores / math.sqrt(q.shape[-1])
    mask_b = mask[None] if mask.ndim == 2 else mask      # -> (B|1, T, S)
    scores = jnp.where(mask_b[:, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


# Below this the materialized-score path is cheaper to compile and its
# O(T^2) scores are small; above it the blockwise paths keep memory bounded.
_FLASH_MIN_T = 512


def _chunked_key_pass(qf, q_pos, k_pad, v_pad, *, chunk: int, n_chunks: int,
                      base_pos, valid_len: int, far, carry, scale: float,
                      remat: bool):
    """Online-softmax accumulation over the key chunks of ONE padded block —
    the inner loop both the ring step and the single-device chunked path
    share (one copy of the sentinel/masking convention). ``base_pos`` is
    the block's global position offset; overhang keys (j >= valid_len) get
    the ``far`` sentinel the causal test rejects. With ``remat`` each
    chunk's probabilities are recomputed in backward instead of saved —
    without it, reverse-mode AD stores every (q, k)-chunk softmax block and
    the memory win evaporates exactly at long-context training sizes."""
    update = (jax.checkpoint(_online_softmax_update) if remat
              else _online_softmax_update)

    def body(c, inner):
        m, l, acc = inner
        k_c = jax.lax.dynamic_slice_in_dim(k_pad, c * chunk, chunk, 1)
        v_c = jax.lax.dynamic_slice_in_dim(v_pad, c * chunk, chunk, 1)
        j = c * chunk + jnp.arange(chunk)
        k_pos = jnp.where(j < valid_len, base_pos + j, far)
        return update(qf, k_c, v_c, q_pos, k_pos, m, l, acc, scale)

    return jax.lax.fori_loop(0, n_chunks, body, carry)


def chunked_causal_attention(q, k, v, q_chunk: int = 512,
                             key_chunk: int = 1024) -> jax.Array:
    """Memory-efficient causal attention in pure XLA: a static loop over
    query chunks, online softmax over key chunks — peak score memory
    O(q_chunk * key_chunk) per head instead of O(T^2), in backward too
    (chunk updates are rematerialized). Unlike the Pallas flash kernel this
    is reverse-differentiable and GSPMD-partitionable (plain einsums shard
    over heads under tensor parallelism), so it is the long-sequence path
    TRAINING and TP use. Each query chunk only visits key chunks at or
    below the diagonal (the loop bound is static per chunk), so no FLOPs
    go to fully-masked blocks. Ragged tails are handled like the ring's:
    padded keys carry a sentinel position; padded queries are sliced away.
    """
    B, T, H, d = q.shape
    scale = 1.0 / math.sqrt(d)
    qc = min(q_chunk, T)
    kc = min(key_chunk, T)
    n_q = -(-T // qc)
    n_k = -(-T // kc)
    q_pad = jnp.pad(q, ((0, 0), (0, n_q * qc - T), (0, 0), (0, 0)))
    k_pad = jnp.pad(k, ((0, 0), (0, n_k * kc - T), (0, 0), (0, 0)))
    v_pad = jnp.pad(v, ((0, 0), (0, n_k * kc - T), (0, 0), (0, 0)))
    far = T + 1  # sentinel: beyond every real query position

    outs = []
    for qi in range(n_q):  # static: per-chunk causal bounds, differentiable
        q_c = jax.lax.dynamic_slice_in_dim(q_pad, qi * qc, qc, 1)
        qf = q_c.astype(jnp.float32)
        q_pos = qi * qc + jnp.arange(qc)
        carry = (jnp.full((B, H, qc), -jnp.inf, jnp.float32),
                 jnp.zeros((B, H, qc), jnp.float32),
                 jnp.zeros((B, H, qc, d), jnp.float32))
        # key chunks entirely above the diagonal contribute nothing
        n_k_i = min(n_k, -(-(qi * qc + qc) // kc))
        _, l, acc = _chunked_key_pass(
            qf, q_pos, k_pad, v_pad, chunk=kc, n_chunks=n_k_i, base_pos=0,
            valid_len=T, far=far, carry=carry, scale=scale, remat=True)
        out = acc / jnp.maximum(l, 1e-30)[..., None]          # (B,H,qc,d)
        outs.append(out.transpose(0, 2, 1, 3))                # (B,qc,H,d)

    out = jnp.concatenate(outs, axis=1)
    return out[:, :T].astype(q.dtype)


def _expand_kv_heads(t: jax.Array, rep: int) -> jax.Array:
    """GQA/MQA kv -> full query-head width (HF repeat_kv semantics). The
    ONE expansion idiom — the flash kernel never calls it (its index map
    reads narrow kv directly); the XLA attention paths and the flash
    backward do."""
    return t if rep == 1 else jnp.repeat(t, rep, axis=2)


@jax.custom_vjp
def _flash_attention_diff(q, k, v):
    """Flash forward with a differentiable backward: ``pallas_call`` defines
    no VJP, so the backward pass re-derives gradients through
    ``chunked_causal_attention`` (the exact same function, computed in
    bounded-memory XLA). External callers differentiating an auto-dispatched
    long-sequence ``forward()`` therefore get real gradients instead of an
    opaque Pallas AD error (round-2 advisor finding). k/v may be at their
    narrow GQA width (the kernel maps heads to groups; no expansion is
    materialized) — the backward expands inside the vjp, whose repeat
    transpose sums dk/dv over each group."""
    from fraud_detection_tpu.ops.attention import auto_interpret, flash_attention

    return flash_attention(q, k, v, interpret=auto_interpret())


def _flash_diff_fwd(q, k, v):
    return _flash_attention_diff(q, k, v), (q, k, v)


def _flash_diff_bwd(res, g):
    q, k, v = res
    rep = q.shape[2] // k.shape[2]

    def ref(q_, k_, v_):
        return chunked_causal_attention(q_, _expand_kv_heads(k_, rep),
                                        _expand_kv_heads(v_, rep))

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash_attention_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def causal_attention(q, k, v, use_flash: Optional[bool] = None) -> jax.Array:
    """Full-sequence causal attention, dispatched by length and context:

    * short sequences — materialized scores (cheapest to compile);
    * long + ``use_flash`` allowed — the Pallas flash kernel
      (ops/attention.py), wrapped so its backward runs through the chunked
      XLA path (differentiable even under auto-dispatch);
    * long + ``use_flash=False`` (training, tensor parallelism) —
      ``chunked_causal_attention``: same bounded memory, one fused
      forward+backward program, and GSPMD shards its einsums over heads
      (``pallas_call`` has no partitioning rule, so the flash path would
      all-gather head-sharded activations).

    ``use_flash``: None = auto by length; model-axis-sharded callers must
    pass False.

    k/v may arrive at their narrow GQA/MQA width (fewer heads than q):
    the flash path consumes them natively — no 8x K/V expansion is
    materialized or streamed on MQA — and the XLA paths expand here, so
    every branch sees identical math."""
    long_seq = q.shape[1] >= _FLASH_MIN_T
    if use_flash is None:
        use_flash = long_seq
    if use_flash:
        return _flash_attention_diff(q, k, v)
    rep = q.shape[2] // k.shape[2]
    k, v = _expand_kv_heads(k, rep), _expand_kv_heads(v, rep)
    if long_seq:
        return chunked_causal_attention(q, k, v)
    causal = jnp.tril(jnp.ones((q.shape[1], q.shape[1]), bool))
    return _attend(q, k, v, causal)


# ---------------------------------------------------------------------------
# ring attention (sequence parallelism)
# ---------------------------------------------------------------------------

def _online_softmax_update(qf, k_part, v_part, q_pos, k_pos, m, l, acc,
                           scale: float):
    """One online-softmax accumulation against a slice of keys/values —
    the shared inner math of the ring step and its key-chunked variant."""
    scores = jnp.einsum("bthd,bshd->bhts", qf, k_part.astype(jnp.float32)) * scale
    causal = q_pos[:, None] >= k_pos[None, :]                # (T, S_part)
    scores = jnp.where(causal[None, None], scores, -jnp.inf)
    blk_max = jnp.max(scores, axis=-1)                       # (B,H,T)
    m_new = jnp.maximum(m, blk_max)
    # guard fully-masked rows (no valid key yet in this slice)
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(scores - m_safe[..., None])
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    correction = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = l * correction + jnp.sum(p, axis=-1)
    acc_new = (acc * correction[..., None]
               + jnp.einsum("bhts,bshd->bthd", p, v_part.astype(jnp.float32))
                 .transpose(0, 2, 1, 3))
    return m_new, l_new, acc_new


# Peak-memory knob for the ring step: scores materialize (B, H, T_loc,
# chunk) instead of (B, H, T_loc, T_loc) — without it a 4k-per-device shard
# costs 512MB of f32 scores per head-8 step, defeating the ring's O(T/n)
# memory promise on exactly the long-transcript workloads it exists for.
_RING_KEY_CHUNK = 2048


def _ring_attention_sharded(q, k, v, *, axis_name: str, blocks_per_ring: int,
                            scale: float, key_chunk: int = _RING_KEY_CHUNK,
                            batch_axis: Optional[str] = None):
    """Per-shard body (runs under shard_map): exact causal attention with K/V
    blocks rotating around the ring, flash-style online softmax; within a
    step, keys are processed in ``key_chunk`` slices so score memory stays
    O(T_loc * key_chunk).

    q: (B, T_loc, H, d) — this device's sequence shard; k/v may be at
    their NARROW GQA/MQA width (B, T_loc, Hkv, d): blocks transit the ring
    narrow — 1/rep of the ICI bytes per rotation (8x less for Gemma-2B's
    MQA) — and expand to query width only on arrival, for the local
    chunk attend. Device r owns global positions [r*T_loc, (r+1)*T_loc).
    """
    if key_chunk < 1:
        raise ValueError(f"key_chunk must be >= 1, got {key_chunk}")
    idx = jax.lax.axis_index(axis_name)
    B, T, H, d = q.shape
    rep = H // k.shape[2]
    qf = q.astype(jnp.float32)
    # Ceil-division chunking (T is static): the last chunk may overhang the
    # block; overhang keys are masked out via a sentinel position, so any
    # T_loc — prime lengths included — keeps chunk ~= key_chunk instead of
    # degrading to tiny divisors.
    chunk = min(T, key_chunk)
    n_chunks = -(-T // chunk)
    pad = n_chunks * chunk - T
    # Sentinel above every real global position: the causal mask rejects it.
    far = blocks_per_ring * T + 1

    def step(s, carry):
        k_blk, v_blk, m, l, acc = carry
        # after s rotations device idx holds the block produced by idx - s
        src = (idx - s) % blocks_per_ring
        q_pos = idx * T + jnp.arange(T)
        # Expand AFTER transit: the block rode the ring at narrow width.
        k_full = _expand_kv_heads(k_blk, rep)
        v_full = _expand_kv_heads(v_blk, rep)
        if n_chunks == 1:
            k_pos = src * T + jnp.arange(T)
            m, l, acc = _online_softmax_update(
                qf, k_full, v_full, q_pos, k_pos, m, l, acc, scale)
        else:
            k_pad = jnp.pad(k_full, ((0, 0), (0, pad), (0, 0), (0, 0)))
            v_pad = jnp.pad(v_full, ((0, 0), (0, pad), (0, 0), (0, 0)))
            m, l, acc = _chunked_key_pass(
                qf, q_pos, k_pad, v_pad, chunk=chunk, n_chunks=n_chunks,
                base_pos=src * T, valid_len=T, far=far, carry=(m, l, acc),
                scale=scale, remat=False)
        k_next = jax.lax.ppermute(
            k_blk, axis_name, [(i, (i + 1) % blocks_per_ring) for i in range(blocks_per_ring)])
        v_next = jax.lax.ppermute(
            v_blk, axis_name, [(i, (i + 1) % blocks_per_ring) for i in range(blocks_per_ring)])
        return k_next, v_next, m, l, acc

    # pvary: the accumulators become device-varying on the first iteration, so
    # their carry types must be marked varying over the ring axis up front.
    # pcast is the jax>=0.9 spelling; fall back to pvary (same marking,
    # deprecated in 0.9) so the declared jax>=0.8 floor actually runs.
    vary = (axis_name,) if batch_axis is None else (axis_name, batch_axis)
    _pcast = getattr(jax.lax, "pcast", None)
    mark = (partial(_pcast, axis_name=vary, to="varying") if _pcast is not None
            else partial(jax.lax.pvary, axis_name=vary))
    m0 = mark(jnp.full((B, H, T), -jnp.inf, jnp.float32))
    l0 = mark(jnp.zeros((B, H, T), jnp.float32))
    acc0 = mark(jnp.zeros((B, H, T, d), jnp.float32))
    _, _, m, l, acc = jax.lax.fori_loop(
        0, blocks_per_ring, step, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]             # (B,H,T,d)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)         # (B,T,H,d)


def _ulysses_sharded(q, k, v, *, axis_name: str, causal_mask):
    """Per-shard body: all-to-all heads<->sequence, local full attention,
    all-to-all back. q/k/v arrive (B, T/n, H, d); after the first collective
    each device holds ALL T positions for H/n heads."""
    def to_heads(x):   # (B, T/n, H, d) -> (B, T, H/n, d)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def to_seq(x):     # (B, T, H/n, d) -> (B, T/n, H, d)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    attn = _attend(to_heads(q), to_heads(k), to_heads(v), causal_mask)
    return to_seq(attn)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                      axis_name: str = SEQ_AXIS,
                      batch_axis: Optional[str] = None) -> jax.Array:
    """All-to-all sequence parallelism (the Ulysses layout) — the second SP
    strategy next to ``ring_attention``. Two collectives per call re-shard
    heads<->sequence so every device runs plain full causal attention for
    its H/n head group over the WHOLE sequence: cheaper in ICI traffic than
    the ring's n-step rotation when heads divide evenly and the full (T, T)
    score block for H/n heads fits on a device; the ring (with key
    chunking) remains the memory-bounded choice for extreme T.

    q/k/v: (B, T, H, d) global; T and H must divide by the axis size.
    Narrow GQA/MQA k/v are accepted and expanded HERE: the head<->sequence
    all-to-all splits the head axis, which needs full query width (the
    ring, which never reshards heads, ships kv narrow instead).
    """
    n = mesh.shape[axis_name]
    B, T, H, d = q.shape
    k = _expand_kv_heads(k, H // k.shape[2])
    v = _expand_kv_heads(v, H // v.shape[2])
    if T % n or H % n:
        raise ValueError(
            f"ulysses_attention needs T ({T}) and H ({H}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring_attention otherwise")
    causal = jnp.tril(jnp.ones((T, T), bool))
    body = partial(_ulysses_sharded, axis_name=axis_name, causal_mask=causal)
    spec = P(batch_axis, axis_name, None, None)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   axis_name: str = SEQ_AXIS,
                   key_chunk: int = _RING_KEY_CHUNK,
                   batch_axis: Optional[str] = None) -> jax.Array:
    """Exact causal attention with the sequence sharded over ``axis_name``.

    q: (B, T, H, d) global; k/v may be at their narrow GQA/MQA width
    (B, T, Hkv, d) — they rotate the ring NARROW (1/rep of the ICI bytes;
    8x less for MQA) and expand per arrival. T must divide by the axis
    size. ``key_chunk`` bounds per-step score memory (see
    ``_RING_KEY_CHUNK``).
    ``batch_axis``: on a 2-D (data, seq) mesh, also shard the batch dim —
    without it the shard_map spec would silently REPLICATE the batch across
    the data axis (an all-gather of every dp-sharded activation).
    """
    n = mesh.shape[axis_name]
    scale = 1.0 / math.sqrt(q.shape[-1])
    body = partial(_ring_attention_sharded, axis_name=axis_name,
                   blocks_per_ring=n, scale=scale, key_chunk=key_chunk,
                   batch_axis=batch_axis)
    spec = P(batch_axis, axis_name, None, None)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def forward(params: Params, tokens: jax.Array, cfg: TransformerConfig,
            *, positions: Optional[jax.Array] = None,
            kv_cache: Optional[Dict[str, jax.Array]] = None,
            cache_len: Optional[jax.Array] = None,
            valid_from: Optional[jax.Array] = None,
            seq_mesh: Optional[Mesh] = None,
            sp_impl: str = "ring",
            use_flash: Optional[bool] = None,
            logits_last_only: bool = False) -> Tuple[jax.Array, Optional[Dict]]:
    """Logits for a token batch (B, T) -> (B, T, V).

    ``logits_last_only``: emit logits for the LAST position only —
    (B, 1, V). The decode prefill uses this: full-sequence logits cost
    B*T*V f32 (a 64-row batch of ~1000-token transcripts would materialize
    ~63GB and OOM the chip) and T times the output-head FLOPs, while
    sampling only ever reads position -1.

    Three modes:
      * full-sequence (kv_cache None, seq_mesh None): causal attention —
        the flash kernel for long sequences (``use_flash`` None = auto;
        pass False when params are model-axis sharded, see
        ``causal_attention``);
      * sequence-parallel (seq_mesh given): exact attention with T sharded
        over the mesh "seq" axis (prefill/scoring of long transcripts);
        ``sp_impl`` picks the strategy — "ring" (K/V rotation, memory-
        bounded) or "ulysses" (two all-to-alls, head-partitioned);
      * incremental (kv_cache given): T == 1 decode step against the cache;
        returns the updated cache. ``valid_from`` (B,) marks each row's
        first REAL cache slot — left-padded batched decode masks everything
        before it (uneven prompt lengths share one cache layout).
    """
    B, T = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    x = _embed_rows(params["embed"], tokens, cfg.dtype)
    if cfg.embed_scale != 1.0:  # Gemma scales embeddings by sqrt(D)
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    new_cache: Optional[Dict[str, jax.Array]] = {} if kv_cache is not None else None
    act = jax.nn.silu if cfg.activation == "silu" else partial(
        jax.nn.gelu, approximate=True)
    rep = cfg.n_heads // cfg.kv_heads  # GQA: queries per kv head
    expand_kv = partial(_expand_kv_heads, rep=rep)

    for l in range(cfg.n_layers):
        h = rms_norm(x, params[f"l{l}.ln1"], cfg.rms_eps)
        q = _mm("btD,Dhd->bthd", h, params[f"l{l}.wq"], cfg.dtype)
        k = _mm("btD,Dhd->bthd", h, params[f"l{l}.wk"], cfg.dtype)
        v = _mm("btD,Dhd->bthd", h, params[f"l{l}.wv"], cfg.dtype)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)

        if kv_cache is not None:
            # decode: append this step's k/v at cache_len, attend over prefix
            # (cache stays at Hkv width — the GQA memory win — and expands
            # only for the score einsum)
            ck = jax.lax.dynamic_update_slice(
                kv_cache[f"l{l}.k"], k, (0, cache_len, 0, 0))
            cv = jax.lax.dynamic_update_slice(
                kv_cache[f"l{l}.v"], v, (0, cache_len, 0, 0))
            new_cache[f"l{l}.k"], new_cache[f"l{l}.v"] = ck, cv
            S = ck.shape[1]
            # causal within the appended block: row t sees keys <= cache_len+t
            valid = jnp.arange(S)[None, :] <= (cache_len + jnp.arange(T))[:, None]
            if valid_from is not None:  # (B,): left-pad slots are not real
                # Keep each query's OWN slot visible even in the pad region:
                # a fully-masked row softmaxes to NaN, and NaN values poison
                # later layers through 0-weighted (0 * NaN) attention sums.
                # Pad-query outputs are garbage-but-finite and never read.
                own = (jnp.arange(S)[None, :]
                       == (cache_len + jnp.arange(T))[:, None])  # (T, S)
                valid = ((valid[None]
                          & (jnp.arange(S)[None, None, :]
                             >= valid_from[:, None, None]))
                         | own[None])
            attn = _attend(q, expand_kv(ck), expand_kv(cv), valid)
        elif seq_mesh is not None:
            # On a (data, seq) training mesh the batch dim rides the data
            # axis through the SP body; a pure-seq serving mesh has none.
            # kv pass at native GQA width: the ring ships them narrow over
            # ICI (1/rep of the bytes per rotation) and expands on arrival;
            # ulysses expands at entry (its all-to-all splits heads).
            b_axis = DATA_AXIS if DATA_AXIS in seq_mesh.axis_names else None
            sp = (ulysses_attention if sp_impl == "ulysses"
                  else ring_attention)
            attn = sp(q, k, v, seq_mesh, batch_axis=b_axis)
        else:
            # kv at native GQA width: causal_attention expands only on the
            # XLA branches; the flash kernel maps heads to groups directly.
            attn = causal_attention(q, k, v, use_flash)

        x = x + _mm("bthd,hdD->btD", attn, params[f"l{l}.wo"], cfg.dtype)
        h2 = rms_norm(x, params[f"l{l}.ln2"], cfg.rms_eps)
        gate = act(_mm("btD,DF->btF", h2, params[f"l{l}.w_gate"], cfg.dtype))
        up = _mm("btD,DF->btF", h2, params[f"l{l}.w_up"], cfg.dtype)
        x = x + _mm("btF,FD->btD", gate * up, params[f"l{l}.w_down"], cfg.dtype)

    x = rms_norm(x, params["ln_f"], cfg.rms_eps)
    if logits_last_only:
        x = x[:, -1:]
    head = params["lm_head"] if not cfg.tie_embeddings else params["embed"]
    if isinstance(head, Q8):
        # (V, 1) per-row scale applied to the f32 logits, same output-side
        # move as _mm — the int8 head streams at int8 width.
        logits = (jnp.einsum("btD,VD->btV", x, head.q.astype(cfg.dtype))
                  .astype(jnp.float32) * head.scale[:, 0])
    else:
        logits = jnp.einsum("btD,VD->btV", x, head).astype(jnp.float32)
    return logits, new_cache


def init_cache(cfg: TransformerConfig, batch: int, max_len: int) -> Dict[str, jax.Array]:
    return {f"l{l}.{t}": jnp.zeros((batch, max_len, cfg.kv_heads, cfg.head_dim), cfg.dtype)
            for l in range(cfg.n_layers) for t in ("k", "v")}


# ---------------------------------------------------------------------------
# slot decode (continuous batching: explain/slotserve/)
#
# The fixed-batch decode below (`_generate_batch_jit`) runs B prompts behind
# ONE barrier: every row pays device steps until the SLOWEST row finishes,
# and a new request waits for the whole batch to drain. These two functions
# are the iteration-level alternative (Orca, OSDI '22): one PERSISTENT
# (slots, S, Hkv, d) KV pool where each row owns a slot, a prompt prefills
# into a free slot at any iteration boundary, and one decode step advances
# every busy slot — per-slot lengths, per-slot retirement, no barrier. The
# host-side slot/queue management lives in explain/slotserve/; these are the
# only device programs it runs (exactly one decode compile for the pool, one
# prefill compile per prompt bucket).
# ---------------------------------------------------------------------------


def _logits_head(x: jax.Array, params: Params, cfg: TransformerConfig) -> jax.Array:
    """Output-head logits for (N, D) features — the Q8 per-row-scale move
    `forward` applies, shared by the slot prefill/decode entries."""
    head = params["lm_head"] if not cfg.tie_embeddings else params["embed"]
    if isinstance(head, Q8):
        return (jnp.einsum("nD,VD->nV", x, head.q.astype(cfg.dtype))
                .astype(jnp.float32) * head.scale[:, 0])
    return jnp.einsum("nD,VD->nV", x, head).astype(jnp.float32)


@partial(jax.jit, static_argnames=("cfg",))
def slot_prefill(params: Params, tokens: jax.Array, length: jax.Array,
                 cfg: TransformerConfig, kv_cache: Dict[str, jax.Array],
                 slot: jax.Array, temperature: jax.Array,
                 rng: jax.Array):
    """Prefill ONE prompt into row ``slot`` of a pooled slot cache.

    ``tokens``: (1, Tp) RIGHT-padded (Tp is the prompt bucket — compile
    count is bounded by the bucket ladder, and ``slot``/``length`` are
    traced so admitting into any slot reuses the same program).
    Padding-region k/v DO land in cache rows [length, Tp) — they are
    garbage, but every later read masks to [0, len] and decode overwrites
    them in order, so they are never attended. Returns
    ``(first_token scalar int32, new_cache)`` — the first sampled token is
    part of the row's output (same convention as ``_generate_batch_jit``:
    sample from the prefill logits, then feed tokens back one step at a
    time)."""
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    x = _embed_rows(params["embed"], tokens, cfg.dtype)
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    act = jax.nn.silu if cfg.activation == "silu" else partial(
        jax.nn.gelu, approximate=True)
    new_cache: Dict[str, jax.Array] = {}
    for l in range(cfg.n_layers):
        h = rms_norm(x, params[f"l{l}.ln1"], cfg.rms_eps)
        q = _mm("btD,Dhd->bthd", h, params[f"l{l}.wq"], cfg.dtype)
        k = _mm("btD,Dhd->bthd", h, params[f"l{l}.wk"], cfg.dtype)
        v = _mm("btD,Dhd->bthd", h, params[f"l{l}.wv"], cfg.dtype)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        # Write this prompt's k/v into the slot's cache rows. Right-padded
        # overhang is masked by length everywhere downstream.
        new_cache[f"l{l}.k"] = jax.lax.dynamic_update_slice(
            kv_cache[f"l{l}.k"], k, (slot, 0, 0, 0))
        new_cache[f"l{l}.v"] = jax.lax.dynamic_update_slice(
            kv_cache[f"l{l}.v"], v, (slot, 0, 0, 0))
        # Causal attention over the prompt itself (padded queries attend
        # real+pad keys at or below their position — garbage-but-finite,
        # and only the length-1 position is ever read).
        attn = causal_attention(q, k, v, use_flash=False)
        x = x + _mm("bthd,hdD->btD", attn, params[f"l{l}.wo"], cfg.dtype)
        h2 = rms_norm(x, params[f"l{l}.ln2"], cfg.rms_eps)
        gate = act(_mm("btD,DF->btF", h2, params[f"l{l}.w_gate"], cfg.dtype))
        up = _mm("btD,DF->btF", h2, params[f"l{l}.w_up"], cfg.dtype)
        x = x + _mm("btF,FD->btD", gate * up, params[f"l{l}.w_down"], cfg.dtype)
    x = rms_norm(x, params["ln_f"], cfg.rms_eps)
    # Logits at the LAST REAL position only (length-1; right padding means
    # it is not at Tp-1) — full (Tp, V) logits would pay T times the head.
    x_last = jax.lax.dynamic_slice_in_dim(x[0], length - 1, 1, 0)  # (1, D)
    logits = _logits_head(x_last, params, cfg)                     # (1, V)
    tok = _sample_token(temperature, logits, rng)
    return tok[0], new_cache


def _slot_step_math(params: Params, cfg: TransformerConfig,
                    kv_cache: Dict[str, jax.Array], tokens: jax.Array,
                    lens: jax.Array, temperature: jax.Array,
                    step_key: jax.Array) -> Tuple[jax.Array, Dict]:
    """The shared single-step math of the slot pool: feed (B,) tokens,
    scatter their k/v at per-slot index ``lens[b]``, attend each row over
    its own prefix [0, lens[b]], sample (B,) next tokens (per-slot
    temperature: greedy rows argmax, sampled rows draw from
    (key, row) — a slot's stream never depends on its neighbors)."""
    B = tokens.shape[0]
    positions = lens[:, None]                                   # (B, 1)
    x = _embed_rows(params["embed"], tokens[:, None], cfg.dtype)
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    act = jax.nn.silu if cfg.activation == "silu" else partial(
        jax.nn.gelu, approximate=True)
    rep = cfg.n_heads // cfg.kv_heads
    rows = jnp.arange(B)
    new_cache: Dict[str, jax.Array] = {}
    for l in range(cfg.n_layers):
        h = rms_norm(x, params[f"l{l}.ln1"], cfg.rms_eps)
        q = _mm("btD,Dhd->bthd", h, params[f"l{l}.wq"], cfg.dtype)
        k = _mm("btD,Dhd->bthd", h, params[f"l{l}.wk"], cfg.dtype)
        v = _mm("btD,Dhd->bthd", h, params[f"l{l}.wv"], cfg.dtype)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        # Per-slot append: row b writes at its own lens[b] (a scatter —
        # the whole point of slots is rows sitting at different lengths).
        ck = kv_cache[f"l{l}.k"].at[rows, lens].set(k[:, 0])
        cv = kv_cache[f"l{l}.v"].at[rows, lens].set(v[:, 0])
        new_cache[f"l{l}.k"], new_cache[f"l{l}.v"] = ck, cv
        S = ck.shape[1]
        # Row b attends its own prefix [0, lens[b]] (the appended token's
        # own slot included — never a fully-masked row, so no NaN).
        valid = (jnp.arange(S)[None, None, :]
                 <= lens[:, None, None])                        # (B, 1, S)
        attn = _attend(q, _expand_kv_heads(ck, rep),
                       _expand_kv_heads(cv, rep), valid)
        x = x + _mm("bthd,hdD->btD", attn, params[f"l{l}.wo"], cfg.dtype)
        h2 = rms_norm(x, params[f"l{l}.ln2"], cfg.rms_eps)
        gate = act(_mm("btD,DF->btF", h2, params[f"l{l}.w_gate"], cfg.dtype))
        up = _mm("btD,DF->btF", h2, params[f"l{l}.w_up"], cfg.dtype)
        x = x + _mm("btF,FD->btD", gate * up, params[f"l{l}.w_down"], cfg.dtype)
    x = rms_norm(x, params["ln_f"], cfg.rms_eps)[:, 0]          # (B, D)
    logits = _logits_head(x, params, cfg)                       # (B, V)
    greedy = jnp.argmax(logits, -1)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    row_keys = jax.vmap(partial(jax.random.fold_in, step_key))(rows)
    drawn = jax.vmap(lambda k_, lg: jax.random.categorical(k_, lg, -1))(
        row_keys, scaled)
    tok = jnp.where(temperature <= 1e-6, greedy, drawn).astype(jnp.int32)
    return tok, new_cache


def _slot_window_loop(params: Params, tokens: jax.Array, lens: jax.Array,
                      active: jax.Array, remaining: jax.Array,
                      cfg: TransformerConfig,
                      kv_cache: Dict[str, jax.Array],
                      temperature: jax.Array, rng: jax.Array,
                      steps: int):
    """The fused multi-step decode loop over a (B, S, Hkv, d) cache layout —
    shared VERBATIM by the contiguous pool (`slot_decode_window`) and the
    paged pool (`paged_decode_window`, which gathers its pages into exactly
    this layout first). One body means the two paths are bit-equal by
    construction, not by test luck."""
    B = tokens.shape[0]
    out0 = jnp.full((B, steps), cfg.EOS, jnp.int32)

    def cond(carry):
        i, _, _, act, _, _, _, _ = carry
        return (i < steps) & jnp.any(act)

    def body(carry):
        i, last, lens_c, act_c, rem, cache, out, n_act = carry
        tok, cache = _slot_step_math(params, cfg, cache, last, lens_c,
                                     temperature,
                                     jax.random.fold_in(rng, i))
        # Rows active this step wrote their fed token's k/v at lens.
        lens_c = lens_c + act_c.astype(jnp.int32)
        n_act = n_act + jnp.sum(act_c.astype(jnp.int32))
        tok = jnp.where(act_c, tok, jnp.int32(cfg.EOS))
        out = jax.lax.dynamic_update_slice(out, tok[:, None], (0, i))
        rem = rem - act_c.astype(jnp.int32)
        act_c = act_c & (tok != cfg.EOS) & (rem > 0)
        return i + 1, tok, lens_c, act_c, rem, cache, out, n_act

    carry = (jnp.int32(0), tokens, lens, active, remaining, kv_cache, out0,
             jnp.int32(0))
    i, _, new_lens, _, _, new_cache, out, n_act = jax.lax.while_loop(
        cond, body, carry)
    return out, new_lens, i, n_act, new_cache


@partial(jax.jit, static_argnames=("cfg", "steps"))
def slot_decode_window(params: Params, tokens: jax.Array, lens: jax.Array,
                       active: jax.Array, remaining: jax.Array,
                       cfg: TransformerConfig,
                       kv_cache: Dict[str, jax.Array],
                       temperature: jax.Array, rng: jax.Array,
                       steps: int):
    """Up to ``steps`` fused decode iterations for the WHOLE slot pool —
    iteration-level scheduling with the per-token dispatch amortized
    (multi-step scheduling: admissions land at window boundaries, which
    is the continuous-batching granularity knob).

    ``tokens``: (B,) last sampled token per slot (written this window);
    ``lens``: (B,) valid cache length per slot; ``active``: (B,) bool —
    inactive slots compute garbage into index ``lens[b]`` (free slots
    keep lens 0) which the next prefill overwrites, and always emit EOS;
    ``remaining``: (B,) per-slot token budget left. A row that samples
    EOS or exhausts its budget FREEZES for the rest of the window (emits
    EOS, writes nothing further) — exactly the `_generate_batch_jit`
    freeze rule — and the loop exits early once every row froze.

    Returns ``(out (B, steps) EOS-padded, new_lens, steps_run,
    active_row_steps, new_cache)``; the host appends each row's tokens
    column-by-column under the same freeze rule, so host and device agree
    bit-for-bit, and steps_run/active_row_steps feed the occupancy
    accounting."""
    return _slot_window_loop(params, tokens, lens, active, remaining, cfg,
                             kv_cache, temperature, rng, steps)


# ---------------------------------------------------------------------------
# paged slot decode (PagedAttention-style KV pool: explain/slotserve/)
#
# The pooled cache above still reserves a worst-case (slots, S, Hkv, d)
# region per slot. The paged layout below replaces it with a flat pool of
# fixed-size KV blocks — per layer/tensor (num_pages, page, Hkv, d) — plus a
# per-slot PAGE TABLE of page ids. Device programs see only gathers and
# scatters by page id (no data-dependent shapes; table shapes are static),
# and the page tables themselves mutate on the HOST side of the iteration
# boundary, so the compiled programs stay shape-stable across any
# allocation pattern. Shared-prefix caching falls out of the indirection:
# several tables may point at the same refcounted read-only pages holding
# the explain template's preamble k/v, prefilled once (PagedAttention /
# RadixAttention, applied to the slot pool). Allocation policy — refcounts,
# copy-on-write, exhaustion preemption — lives with the host-side allocator
# in explain/slotserve/decode.py; nothing here allocates.
# ---------------------------------------------------------------------------


def init_kv_pages(cfg: TransformerConfig, num_pages: int,
                  page_size: int) -> Dict[str, jax.Array]:
    """The paged twin of ``init_cache``: a flat block pool per layer/tensor.
    Page ids index the leading axis; a slot's logical position p lives at
    ``(table[p // page_size], p % page_size)``."""
    return {f"l{l}.{t}": jnp.zeros(
                (num_pages, page_size, cfg.kv_heads, cfg.head_dim), cfg.dtype)
            for l in range(cfg.n_layers) for t in ("k", "v")}


@partial(jax.jit, donate_argnums=(0,))
def copy_kv_page(kv_pages: Dict[str, jax.Array], src: jax.Array,
                 dst: jax.Array) -> Dict[str, jax.Array]:
    """Copy-on-write device copy: page ``src`` -> page ``dst`` across every
    layer/tensor. Traced page ids — one compile covers every COW."""
    return {name: arr.at[dst].set(arr[src]) for name, arr in kv_pages.items()}


def _gather_view(kv_pages: Dict[str, jax.Array],
                 tables: jax.Array) -> Dict[str, jax.Array]:
    """Materialize the contiguous-layout view of ``tables`` (B, n_view):
    (B, n_view*page, Hkv, d) per layer/tensor. Unallocated table slots hold
    filler id 0 — their gathered content is stale pool data, which the
    decode/prefill masks (never attended) and the scatter-back never
    targets (write positions are always table-covered by the allocator)."""
    out = {}
    for name, arr in kv_pages.items():
        num_pages, page, hkv, d = arr.shape
        g = arr[tables]                                  # (B, n_view, P, ...)
        out[name] = g.reshape(tables.shape[0], tables.shape[1] * page, hkv, d)
    return out


@partial(jax.jit, static_argnames=("cfg", "prefix_len"))
def paged_slot_prefill(params: Params, tokens: jax.Array, length: jax.Array,
                       cfg: TransformerConfig,
                       kv_pages: Dict[str, jax.Array], table_row: jax.Array,
                       temperature: jax.Array, rng: jax.Array,
                       prefix_len: int):
    """Prefill ONE prompt suffix into the pages of ``table_row``.

    ``tokens``: (1, Ts) RIGHT-padded suffix — with shared-prefix caching the
    first ``prefix_len`` positions of the row are already resident (read-only
    preamble pages every table points at), so only the transcript suffix is
    computed; ``prefix_len == 0`` is the plain no-sharing path. ``length`` is
    the FULL prompt length (prefix + real suffix), matching the contiguous
    ``slot_prefill`` convention so the sampled-token position is identical.

    ``table_row``: (n_view,) page ids covering at least
    ``prefix_len + Ts`` positions. Suffix k/v scatter into the row's own
    pages; the prefix region is only gathered (COW in the allocator
    guarantees a table never points a WRITE position at a shared page).
    ``prefix_len`` is static: one shared preamble per service -> one
    compile per suffix bucket, same bound as the contiguous ladder.

    Bit-equality with ``slot_prefill``: suffix activations are position-
    wise identical; attention reads [cached prefix k/v ; this suffix's
    k/v] under the same causal mask (row j attends positions <=
    prefix_len + j), and the masked tail pads with exact zeros — the
    zero-pad width invariance the slot tests pin."""
    B, Ts = tokens.shape
    page = next(iter(kv_pages.values())).shape[1]
    positions = jnp.broadcast_to(prefix_len + jnp.arange(Ts), (B, Ts))
    x = _embed_rows(params["embed"], tokens, cfg.dtype)
    if cfg.embed_scale != 1.0:
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    act = jax.nn.silu if cfg.activation == "silu" else partial(
        jax.nn.gelu, approximate=True)
    rep = cfg.n_heads // cfg.kv_heads
    # Static per-suffix-position page/offset mapping: position prefix_len+j
    # lives at (table_row[(prefix_len+j)//page], (prefix_len+j)%page).
    pos = prefix_len + jnp.arange(Ts)
    pids = table_row[pos // page]                        # (Ts,) traced ids
    offs = pos % page
    # Row j attends every resident position at or below its own.
    kv_mask = (jnp.arange(table_row.shape[0] * page)[None, :]
               <= pos[:, None])                          # (Ts, Tkv)
    new_pages: Dict[str, jax.Array] = dict(kv_pages)
    for l in range(cfg.n_layers):
        h = rms_norm(x, params[f"l{l}.ln1"], cfg.rms_eps)
        q = _mm("btD,Dhd->bthd", h, params[f"l{l}.wq"], cfg.dtype)
        k = _mm("btD,Dhd->bthd", h, params[f"l{l}.wk"], cfg.dtype)
        v = _mm("btD,Dhd->bthd", h, params[f"l{l}.wv"], cfg.dtype)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        # Scatter the suffix k/v into the row's own pages (pad-region
        # overhang included — garbage-but-private, masked downstream and
        # overwritten in order by decode, same as the contiguous path).
        pk = new_pages[f"l{l}.k"].at[pids, offs].set(k[0])
        pv = new_pages[f"l{l}.v"].at[pids, offs].set(v[0])
        new_pages[f"l{l}.k"], new_pages[f"l{l}.v"] = pk, pv
        # Gather the row's resident view: prefix pages + the suffix just
        # written. (B=1: table_row[None] is the one-row table.)
        view = _gather_view({"k": pk, "v": pv}, table_row[None])
        attn = _attend(q, _expand_kv_heads(view["k"], rep),
                       _expand_kv_heads(view["v"], rep), kv_mask)
        x = x + _mm("bthd,hdD->btD", attn, params[f"l{l}.wo"], cfg.dtype)
        h2 = rms_norm(x, params[f"l{l}.ln2"], cfg.rms_eps)
        gate = act(_mm("btD,DF->btF", h2, params[f"l{l}.w_gate"], cfg.dtype))
        up = _mm("btD,DF->btF", h2, params[f"l{l}.w_up"], cfg.dtype)
        x = x + _mm("btF,FD->btD", gate * up, params[f"l{l}.w_down"], cfg.dtype)
    x = rms_norm(x, params["ln_f"], cfg.rms_eps)
    # Logits at the last REAL position, suffix-local index length-1-prefix.
    x_last = jax.lax.dynamic_slice_in_dim(
        x[0], length - 1 - prefix_len, 1, 0)                       # (1, D)
    logits = _logits_head(x_last, params, cfg)                     # (1, V)
    tok = _sample_token(temperature, logits, rng)
    return tok[0], new_pages


@partial(jax.jit, static_argnames=("cfg", "steps", "view_len"))
def paged_decode_window(params: Params, tokens: jax.Array, lens: jax.Array,
                        active: jax.Array, remaining: jax.Array,
                        cfg: TransformerConfig,
                        kv_pages: Dict[str, jax.Array], tables: jax.Array,
                        temperature: jax.Array, rng: jax.Array,
                        steps: int, view_len: int):
    """`slot_decode_window` over the paged pool: gather every slot's pages
    into the contiguous (B, view_len, Hkv, d) layout, run the IDENTICAL
    fused window loop (``_slot_window_loop``), then scatter each row's
    newly written positions [lens, new_lens) back to its pages.

    ``view_len`` is the contiguous pool's max_len: the gathered view is
    SLICED to it (the last page may overhang when max_len is not
    page-aligned), so the window loop runs at exactly the contiguous
    attention width — bit-equal by construction, not by reduction-order
    luck.

    ``tables``: (B, n_view) page ids; the allocator guarantees every active
    row's table covers [0, lens + steps) before the call, so scatter-back
    positions are always table-resident. Frozen/inactive rows write
    in-window garbage at their frozen ``lens`` exactly like the contiguous
    path — it is NOT scattered back (the next admit/step overwrites it
    before any attend, so dropping it preserves bit-equality)."""
    B = tokens.shape[0]
    page = next(iter(kv_pages.values())).shape[1]
    n_view = tables.shape[1]
    num_pages = next(iter(kv_pages.values())).shape[0]
    if not 0 < view_len <= n_view * page:
        raise ValueError(f"view_len {view_len} outside (0, "
                         f"{n_view * page}]")
    view = {name: arr[:, :view_len]
            for name, arr in _gather_view(kv_pages, tables).items()}
    out, new_lens, i, n_act, new_view = _slot_window_loop(
        params, tokens, lens, active, remaining, cfg, view, temperature,
        rng, steps)
    # Scatter-back: row b wrote view positions [lens[b], new_lens[b]).
    rows = jnp.arange(B)
    pos = lens[:, None] + jnp.arange(steps)[None, :]               # (B, W)
    valid = pos < new_lens[:, None]
    pidx = jnp.minimum(pos // page, n_view - 1)
    pids = jnp.take_along_axis(tables, pidx, axis=1)
    # Invalid entries get an out-of-range page id: JAX scatter DROPS
    # out-of-bounds writes, so masked positions never touch the pool.
    pids = jnp.where(valid, pids, num_pages)
    offs = pos % page
    pos_c = jnp.minimum(pos, view_len - 1)
    new_pages: Dict[str, jax.Array] = {}
    for name, arr in kv_pages.items():
        vals = new_view[name][rows[:, None], pos_c]        # (B, W, Hkv, d)
        new_pages[name] = arr.at[pids, offs].set(vals)
    return out, new_lens, i, n_act, new_pages


# ---------------------------------------------------------------------------
# generation
# ---------------------------------------------------------------------------

def _sample_token(temperature, logits_1, step_key):
    """Greedy below the temperature epsilon, categorical above — the ONE
    sampling rule the decode path uses. Each row draws from its own key,
    ``fold_in(step_key, row)``, so a row's sample depends only on
    (seed, step, row) — NOT on how many prompts are co-batched (batch-size
    bucketing pads B; a (B, V)-shaped draw would change with the padding)."""
    greedy = jnp.argmax(logits_1, -1)
    scaled = logits_1 / jnp.maximum(temperature, 1e-6)
    row_keys = jax.vmap(partial(jax.random.fold_in, step_key))(
        jnp.arange(logits_1.shape[0]))
    drawn = jax.vmap(lambda k, lg: jax.random.categorical(k, lg, -1))(
        row_keys, scaled)
    return jnp.where(temperature <= 1e-6, greedy, drawn).astype(jnp.int32)


@partial(jax.jit, static_argnames=("cfg", "max_new"))
def _generate_batch_jit(params: Params, prompt: jax.Array, prompt_len: jax.Array,
                        row_real: jax.Array, cfg: TransformerConfig,
                        max_new: int, temperature: jax.Array, rng: jax.Array):
    """Batched decode for UNEVEN prompt lengths. prompt: (B, Tp) LEFT-padded
    so every row's last real token sits at Tp-1 — all rows then share one
    scalar write position per step, while ``valid_from`` masks each row's
    left-pad slots out of attention and RoPE positions stay per-row real
    (negative on pads, which the mask discards). Returns (B, max_new).
    Row b's greedy output matches the B=1 path on the same prompt —
    tests/test_llm.py::test_batched_generation_matches_single."""
    B, Tp = prompt.shape
    max_len = Tp + max_new
    cache = init_cache(cfg, B, max_len)
    valid_from = Tp - prompt_len                               # (B,)
    positions = jnp.arange(Tp)[None, :] - valid_from[:, None]  # real idx; <0 on pads
    logits, cache = forward(params, prompt, cfg, positions=positions,
                            kv_cache=cache, cache_len=jnp.int32(0),
                            valid_from=valid_from, logits_last_only=True)
    last = logits[:, -1]                                       # every row ends at Tp-1
    sample = partial(_sample_token, temperature)
    out0 = jnp.full((B, max_new), cfg.EOS, jnp.int32)

    # while_loop, not scan: once every row has emitted EOS the loop exits —
    # short answers stop paying per-step forwards (unemitted slots stay EOS,
    # which the tokenizers already treat as end-of-text).
    def cond(carry):
        _, _, i, done, _ = carry
        return (i < max_new) & ~jnp.all(done)

    def body(carry):
        cache, last_logits, i, done, out = carry
        # Per-step key derived by counter from the closed-over rng, per-row
        # keys inside _sample_token: output stream for row r is a pure
        # function of (seed, step, r).
        sub = jax.random.fold_in(rng, i)
        tok = sample(last_logits, sub)                         # (B,)
        tok = jnp.where(done, cfg.EOS, tok)                    # freeze done rows
        out = jax.lax.dynamic_update_slice(out, tok[:, None], (0, i))
        done = done | (tok == cfg.EOS)
        pos = prompt_len + i                                   # (B,) real position
        logits, cache = forward(params, tok[:, None], cfg,
                                positions=pos[:, None],
                                kv_cache=cache, cache_len=Tp + i,
                                valid_from=valid_from)
        return cache, logits[:, 0], i + 1, done, out

    # Batch-bucketing dummy rows start DONE — waiting on a garbage row that
    # may never sample EOS would defeat the early exit for every batch whose
    # real size isn't a power of two.
    carry = (cache, last, jnp.int32(0), ~row_real, out0)
    *_, out = jax.lax.while_loop(cond, body, carry)
    return out  # (B, max_new); rows past their EOS hold EOS


class ByteTokenizer:
    """Self-contained byte-level tokenizer (no external vocab)."""

    def __init__(self, cfg: TransformerConfig):
        self.cfg = cfg

    def encode(self, text: str) -> np.ndarray:
        data = text.encode("utf-8")[: self.cfg.max_seq - 2]
        return np.asarray([self.cfg.BOS] + list(data), np.int32)

    def decode(self, tokens) -> str:
        out = bytearray()
        for t in np.asarray(tokens).tolist():
            if t == self.cfg.EOS:
                break
            if 0 <= t < 256:
                out.append(t)
        return out.decode("utf-8", "replace")


@dataclass
class LanguageModel:
    """Params + config + tokenizer behind a text-in/text-out API."""

    cfg: TransformerConfig
    params: Params
    tokenizer: ByteTokenizer = None

    def __post_init__(self):
        if self.tokenizer is None:
            self.tokenizer = ByteTokenizer(self.cfg)

    @classmethod
    def init_random(cls, cfg: Optional[TransformerConfig] = None, seed: int = 0,
                    mesh: Optional[Mesh] = None) -> "LanguageModel":
        cfg = cfg or TransformerConfig()
        params = init_params(jax.random.PRNGKey(seed), cfg)
        if mesh is not None:
            params = shard_params(params, cfg, mesh)
        return cls(cfg, params)

    def quantized(self, *, include_embed: bool = True) -> "LanguageModel":
        """Weight-only int8 copy (see ``quantize_params``): same API, same
        KV cache, ~half the weight bytes per decode step."""
        return LanguageModel(self.cfg,
                             quantize_params(self.params,
                                             include_embed=include_embed),
                             tokenizer=self.tokenizer)

    def generate_tokens(self, prompt_tokens: np.ndarray, *, max_new_tokens: int = 64,
                        temperature: float = 0.0, seed: int = 0) -> np.ndarray:
        """Single-prompt decode — the B=1 case of ``generate_tokens_batch``
        (one decode program to maintain; the batch path's left-pad masking
        degenerates to a no-op at B=1)."""
        return self.generate_tokens_batch(
            [np.asarray(prompt_tokens)], max_new_tokens=max_new_tokens,
            temperature=temperature, seed=seed)[0]

    def generate_tokens_batch(self, prompts, *, max_new_tokens: int = 64,
                              temperature: float = 0.0,
                              seed: int = 0) -> np.ndarray:
        """Decode a batch of UNEVEN-length prompts in one device program
        (one prefill + one early-exit decode loop — a single tunnel round
        trip for the whole batch). Prompts are left-padded to a shared bucket; per-row validity
        masking keeps each row's context exactly its own prompt. Sampling is
        batch-composition invariant: row r's tokens depend only on
        (seed, step, r), not on how many prompts are co-batched. Returns
        (B, max_new_tokens)."""
        n = len(prompts)
        if n == 0:
            return np.zeros((0, max_new_tokens), np.int32)
        # Bucket BOTH dims: prompt length to a multiple of 8 and batch size
        # to a power of two (dummy rows, sliced away) — a live stream's
        # per-batch valid-row count jitters, and each distinct (B, Tp) would
        # otherwise recompile the whole decode scan.
        b_pad = 1 << (n - 1).bit_length()
        lens_list = [len(p) for p in prompts] + [1] * (b_pad - n)
        lens = np.asarray(lens_list, np.int32)
        pad = 8 * ((int(lens.max()) + 7) // 8)
        prompt = np.zeros((b_pad, pad), np.int32)
        for i, p in enumerate(prompts):
            prompt[i, pad - len(p):] = p        # LEFT-padded
        row_real = np.arange(b_pad) < n
        toks = _generate_batch_jit(self.params, jnp.asarray(prompt),
                                   jnp.asarray(lens), jnp.asarray(row_real),
                                   self.cfg, int(max_new_tokens),
                                   jnp.float32(temperature),
                                   jax.random.PRNGKey(seed))
        return np.asarray(toks)[:n]

    def generate_text(self, prompt: str, *, temperature: float = 0.0,
                      max_new_tokens: int = 256, mesh: Optional[Mesh] = None,
                      seed: int = 0) -> str:
        del mesh  # params are already placed; kept for OnPodBackend signature
        toks = self.generate_tokens(self.tokenizer.encode(prompt),
                                    max_new_tokens=max_new_tokens,
                                    temperature=temperature, seed=seed)
        return self.tokenizer.decode(toks)

    def generate_text_batch(self, prompts, *, temperature: float = 0.0,
                            max_new_tokens: int = 256, seed: int = 0):
        """Batch text-in/text-out: explain MANY flagged dialogues per device
        round trip (the reference pays one synchronous DeepSeek HTTPS call
        per message — app_ui.py:207)."""
        toks = self.generate_tokens_batch(
            [self.tokenizer.encode(p) for p in prompts],
            max_new_tokens=max_new_tokens, temperature=temperature, seed=seed)
        return [self.tokenizer.decode(t) for t in toks]
