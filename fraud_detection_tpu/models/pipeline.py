"""Serving pipeline: text in, (label, probability) out — the agent-facing API.

TPU-native replacement for the reference's serve path
(``DeepSeekClassificationAgent.predict_and_get_label``,
/root/reference/utils/agent_api.py:155-175), which ran a full 5-stage Spark job
per single-row DataFrame. Here the host tokenizes/hashes a whole micro-batch
and one jitted program scores it; for logistic models the features are never
materialized (gather/segment-sum fast path, models/linear.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fraud_detection_tpu.checkpoint.spark_artifact import SparkPipelineArtifact
from fraud_detection_tpu.featurize.text import StopWordFilter
from fraud_detection_tpu.featurize.tfidf import (
    HashingTfIdfFeaturizer,
    VocabTfIdfFeaturizer,
)
from fraud_detection_tpu.models import linear as linear_mod
from fraud_detection_tpu.models import trees as trees_mod
from fraud_detection_tpu.models.linear import LogisticRegression
from fraud_detection_tpu.models.trees import TreeEnsemble


@dataclass
class PredictionBatch:
    labels: np.ndarray          # (N,) int32 — 1 = scam
    probabilities: np.ndarray   # (N,) float32 — p(class=1)

    def __iter__(self):
        return iter(zip(self.labels.tolist(), self.probabilities.tolist()))


_DONATION_EFFECTIVE: Optional[bool] = None


def donation_effective() -> bool:
    """Does this backend CONSUME donated input buffers? Probed once per
    process with a tiny program shaped like the serving case (int16 staging
    buffer in, f32 out — sizes never alias). Platforms that implement
    donation free the input at dispatch (the HBM win the serving path
    wants); CPU jax currently keeps the buffer and warns, so the pipeline
    routes through the non-donating twins there and ``donation_hits``
    honestly stays 0."""
    global _DONATION_EFFECTIVE
    if _DONATION_EFFECTIVE is None:
        import warnings

        # flightcheck: ignore[FC201] — one-shot probe; cached in _DONATION_EFFECTIVE
        probe = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32), axis=-1),
                        donate_argnums=(0,))
        x = jnp.zeros((2, 2, 4), jnp.int16)
        jax.block_until_ready(x)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            jax.block_until_ready(probe(x))
        _DONATION_EFFECTIVE = bool(x.is_deleted())
    return _DONATION_EFFECTIVE


def _pack_encoded(enc) -> Optional[np.ndarray]:
    """Stack an EncodedBatch into ONE (B, 2, L) int16 staging array so the
    micro-batch crosses host->device as a single transfer (ids in plane 0,
    uint16 counts bit-cast into plane 1; linear.unpack_rows restores them
    exactly). None when the featurizer widened ids past int16 (num_features
    > 32767) — that configuration keeps the two-array upload."""
    ids = np.asarray(enc.ids)
    counts = np.asarray(enc.counts)
    if ids.dtype != np.int16 or counts.dtype != np.uint16:
        return None
    return np.stack([ids, counts.view(np.int16)], axis=1)


def unpack_packed_host(packed: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host inverse of ``_pack_encoded``: (B, 2, L) int16 -> (int16 ids,
    uint16 counts). The device featurize path's parity surface
    (featurize/device.py) round-trips through this."""
    packed = np.asarray(packed)
    return packed[:, 0, :], packed[:, 1, :].view(np.uint16)


class DeviceStats:
    """Per-pipeline device-path counters (the ``device`` block of engine
    health): host->device crossings, donation hits, and what is pinned
    HBM-resident. Single-writer — the dispatching thread — with racy reads
    from health pollers by design (a monitoring sample, like StreamStats)."""

    __slots__ = ("uploads", "upload_bytes", "chunks", "donated",
                 "pinned_bytes", "pins", "int8", "mesh_devices", "_rungs",
                 "featurize_path", "feat_bytes_in", "feat_rows",
                 "truncated_rows")

    def __init__(self, int8: bool = False, mesh_devices: int = 0):
        self.uploads = 0        # host->device transfer events
        self.upload_bytes = 0
        self.chunks = 0         # micro-batch chunks dispatched
        self.donated = 0        # chunks dispatched through a donating program
        self.pinned_bytes = 0   # model-side bytes made device-resident
        self.pins = 0           # pin_device() calls (1/version; re-pin on swap)
        self.int8 = int8
        # Mesh data-parallel scoring (parallel/serving.py): chips on the
        # mesh's data axis (0 = single-device path), and every distinct
        # padded row count dispatched — prewarm populates it, so health
        # shows which per-chip rungs are compiled BEFORE traffic arrives.
        self.mesh_devices = mesh_devices
        self._rungs: set = set()
        # Device-side featurization (ops/featurize_kernel.py): which path
        # featurize actually RUNS ("host" = the classic C++/Python leg,
        # "pallas" = compiled kernel, "interpret" = interpreter mode), raw
        # bytes shipped instead of packed ids+counts, and rows whose UTF-8
        # exceeded the byte width (truncated at a codepoint boundary —
        # counted, never silent).
        self.featurize_path = "host"
        self.feat_bytes_in = 0
        self.feat_rows = 0
        self.truncated_rows = 0

    def record_chunk(self, nbytes: int, transfers: int = 1,
                     rows: Optional[int] = None) -> None:
        self.chunks += 1
        self.uploads += transfers
        self.upload_bytes += nbytes
        if rows:
            self._rungs.add(rows)   # set.add is atomic; snapshot copies

    def record_featurize(self, nbytes: int, rows: int, truncated: int) -> None:
        """One device-featurized chunk: raw bytes in, rows covered, rows
        byte-truncated (single-writer, like record_chunk)."""
        self.feat_bytes_in += nbytes
        self.feat_rows += rows
        self.truncated_rows += truncated

    def per_chip_rungs(self) -> list:
        """Distinct padded row counts dispatched, PER CHIP on the data
        axis (== the global rungs on the single-device path)."""
        dp = max(1, self.mesh_devices)
        return sorted({-(-r // dp) for r in self._rungs})

    def snapshot(self) -> dict:
        chunks = self.chunks
        return {
            "uploads": self.uploads,
            "upload_bytes": self.upload_bytes,
            "chunks": chunks,
            "uploads_per_chunk": (round(self.uploads / chunks, 3)
                                  if chunks else None),
            "donation_hits": self.donated,
            "pinned_bytes": self.pinned_bytes,
            "model_pins": self.pins,
            "int8": self.int8,
            "mesh_devices": self.mesh_devices,
            "per_chip_rungs": self.per_chip_rungs(),
            "featurize_path": self.featurize_path,
            "bytes_in_per_row": (round(self.feat_bytes_in / self.feat_rows, 1)
                                 if self.feat_rows else None),
            "truncated_rows": self.truncated_rows,
        }


class PendingPrediction:
    """Unresolved device results from ``ServingPipeline.predict_async``.

    Holds per-chunk (probability, valid_count) device arrays whose host copy
    was already initiated asynchronously at dispatch; ``resolve()`` blocks on
    the device and returns host numpy arrays. Only p(class=1) crosses the
    device->host link — labels come from the identical ``p > threshold``
    comparison on the host (for trees, argmax over the normalized binary
    proba reduces to the same comparison)."""

    def __init__(self, parts: List[Tuple[object, int]], threshold: float = 0.5,
                 argmax: bool = False):
        self._parts = parts
        self.threshold = threshold
        self.argmax = argmax  # parts hold full (B, C) probas (multiclass trees)

    def resolve(self) -> PredictionBatch:
        if not self._parts:
            return PredictionBatch(np.empty(0, np.int32), np.empty(0, np.float32))
        host = np.concatenate([np.asarray(p)[:n] for p, n in self._parts])
        if self.argmax:
            labels = np.argmax(host, axis=-1).astype(np.int32)
            probs = host[:, 1].astype(np.float32)
        else:
            probs = host
            labels = (probs > np.float32(self.threshold)).astype(np.int32)
        return PredictionBatch(labels, probs)


class ServingPipeline:
    """Featurizer + classifier bound together behind ``predict(texts)``.

    Use ``from_spark_artifact`` to serve the reference's shipped model with
    bit-parity semantics, or construct directly from a native featurizer +
    model pair trained by this framework.
    """

    def __init__(self, featurizer: HashingTfIdfFeaturizer,
                 model: "LogisticRegression | TreeEnsemble",
                 fold_idf: bool = True, batch_size: int = 256, mesh=None,
                 int8: bool = False, featurize_device=False,
                 featurize_width: Optional[int] = None,
                 featurize_tokens: Optional[int] = None):
        self.featurizer = featurizer
        self.batch_size = batch_size
        self.mesh = mesh  # data-parallel serving: rows sharded on "data"
        # Padding-bucket ladder (sched/batcher.py): when set (ascending
        # rungs, e.g. (64, 256, 1024)), a partial chunk pads to the smallest
        # rung that fits instead of to batch_size — small batches pay small
        # device programs, and the rung set is the FIXED menu of compiled
        # shapes (pre-warmed at startup so the hot path never compiles).
        # None keeps the single batch_size shape of the bare pipeline.
        self.pad_ladder: Optional[Tuple[int, ...]] = None
        self.model = model
        if isinstance(model, LogisticRegression):
            # Fold IDF into the weights so the sparse fast path sees raw counts.
            self._fused_model: Optional[LogisticRegression] = (
                model.fold_idf(featurizer.idf_array()) if fold_idf else model)
        else:
            # Trees branch on absolute feature values: needs the dense TF-IDF
            # matrix (one scatter + traversal, still one device program).
            self._fused_model = None
        self._tree_idf = None  # device IDF cache for the tree fast path
        # int8 scoring variant (docs/serving.md): symmetric per-block
        # quantization of the fused weights (models/linear.py
        # quantize_weights). Rides the packed upload path; fp32 parity
        # pinned in tests/test_device_path.py.
        self.int8 = bool(int8)
        self._q8 = None
        if self.int8:
            if self._fused_model is None:
                raise ValueError(
                    "int8 scoring requires a LogisticRegression pipeline — "
                    "tree ensembles serve fp32 (their traversal compares "
                    "thresholds, not dot products)")
            self._q8 = linear_mod.quantize_weights(self._fused_model)
        if mesh is not None:
            dp = int(dict(mesh.shape).get("data", 1))
        else:
            dp = 0
        self.device_stats = DeviceStats(int8=self.int8, mesh_devices=dp)
        # Device-side featurization (ops/featurize_kernel.py + featurize/
        # device.py): the host ships a fixed-width raw-byte tensor and ONE
        # jitted program runs tokenize/murmur-hash/count/pack + scoring —
        # the featurize leg leaves the host CPU entirely. ``featurize_device``
        # accepts False, True (compiled Pallas; on a non-TPU backend the
        # build REFUSES and the pipeline honestly keeps the host path —
        # ``DeviceStats.featurize_path`` says which ran) or "interpret"
        # (force interpreter mode: parity tests and benches off-TPU).
        self._dev_feat = None
        self.featurize_unavailable_reason: Optional[str] = None
        if featurize_device:
            from fraud_detection_tpu.featurize.device import (
                DeviceFeaturizeUnavailable, DeviceFeaturizer)

            try:
                self._dev_feat = DeviceFeaturizer(
                    featurizer,
                    **({"width": featurize_width}
                       if featurize_width is not None else {}),
                    **({"tokens": featurize_tokens}
                       if featurize_tokens is not None else {}),
                    interpret=(True if featurize_device == "interpret"
                               else None))
                self.device_stats.featurize_path = self._dev_feat.path
            except DeviceFeaturizeUnavailable as e:
                self.featurize_unavailable_reason = str(e)
        # Donate per-batch staging buffers into the scoring program when the
        # platform consumes them (probed once; False on CPU).
        self._donate = donation_effective()
        self._pinned_version: Optional[object] = None

    def _pad_rows(self, n: int) -> int:
        """Row-padding target for an n-row chunk: the smallest ladder rung
        that fits (ladder configured), else batch_size (the bare contract)."""
        ladder = self.pad_ladder
        if ladder:
            for b in ladder:
                if n <= b:
                    return b
        return self.batch_size

    @property
    def fused_model(self) -> LogisticRegression:
        """The serving model with IDF folded into the weights (raw-count input)."""
        if self._fused_model is None:
            raise TypeError("fused sparse scoring only applies to LogisticRegression")
        return self._fused_model

    @classmethod
    def from_checkpoint(cls, path: str, batch_size: int = 256,
                        mesh=None) -> "ServingPipeline":
        """Load a native checkpoint directory (checkpoint/native.py layout)."""
        from fraud_detection_tpu.checkpoint.native import load_checkpoint

        featurizer, model = load_checkpoint(path)
        return cls(featurizer, model, batch_size=batch_size, mesh=mesh)

    @classmethod
    def from_spark_artifact(cls, artifact: SparkPipelineArtifact,
                            batch_size: int = 256,
                            mesh=None) -> "ServingPipeline":
        """Serve any reference artifact shape: the shipped HashingTF +
        LogisticRegression pipeline (SURVEY.md §2.2) AND the training
        script's CountVectorizer + tree pipelines
        (fraud_detection_spark.py:47-91, saved at :389-393 — quirk Q1)."""
        from fraud_detection_tpu.checkpoint.spark_artifact import RegexTokenizerStage

        for s in artifact.stages:
            if isinstance(s, RegexTokenizerStage):
                raise NotImplementedError(
                    "artifact uses RegexTokenizer; only plain Tokenizer semantics "
                    f"are implemented (pattern={s.pattern!r}, gaps={s.gaps})")
        htf = artifact.hashing_tf
        cv = artifact.count_vectorizer
        idf_stage = artifact.idf
        lr = artifact.logistic_regression
        tree = artifact.tree_ensemble
        sw = artifact.stopwords
        stop = StopWordFilter(sw.stopwords, sw.case_sensitive) if sw else StopWordFilter()
        idf = None if idf_stage is None else idf_stage.idf.astype(np.float32)
        if htf is not None:
            featurizer: HashingTfIdfFeaturizer = HashingTfIdfFeaturizer(
                num_features=htf.num_features, idf=idf, binary_tf=htf.binary,
                stop_filter=stop, remove_stopwords=sw is not None)
        elif cv is not None:
            featurizer = VocabTfIdfFeaturizer(
                vocabulary=cv.vocabulary, min_tf=cv.min_tf, idf=idf,
                binary_tf=cv.binary, stop_filter=stop,
                remove_stopwords=sw is not None)
        else:
            raise ValueError(
                "artifact has no HashingTF or CountVectorizerModel stage "
                f"(got {[type(s).__name__ for s in artifact.stages]})")
        if lr is not None:
            model: "LogisticRegression | TreeEnsemble" = LogisticRegression.from_arrays(
                lr.coefficients, lr.intercept, threshold=lr.threshold)
        elif tree is not None:
            model = trees_mod.from_spark_stage(tree)
        else:
            raise ValueError(
                "artifact has no LogisticRegression or tree classifier stage "
                f"(got {[type(s).__name__ for s in artifact.stages]})")
        return cls(featurizer, model, fold_idf=True, batch_size=batch_size,
                   mesh=mesh)

    def predict_json_async(self, values: Sequence[bytes], text_field: str = "text"
                           ) -> Optional[Tuple["PendingPrediction", np.ndarray,
                                               np.ndarray, np.ndarray,
                                               Optional[list]]]:
        """Raw-JSON fast path: score Kafka message bytes without Python-side
        json.loads (featurize/tfidf.py ``encode_json`` — one native pass from
        message bytes to hashed sparse rows).

        Returns ``(pending, status, span_start, span_len, splice_ctxs)``
        where the pending prediction covers ALL rows positionally (row i =
        values[i]; status 0 rows are all-padding and score as garbage the
        caller must discard), or None when unavailable (no native library or
        vocabulary featurizer). Tree models ride the same native encode: the
        hashed sparse rows scatter to dense TF-IDF and traverse the ensemble
        in one device program (matching the reference's primary trained
        family, fraud_detection_spark.py:56-91 / Q1). The spans locate each
        message's raw string literal for zero-copy output framing
        (stream/engine.py); ``splice_ctxs`` is a list of per-chunk
        ``(marshalled char*[] array, chunk_len)`` for native frame assembly
        (``featurize/native.py build_frames``), or None when any chunk's
        context is unavailable."""
        if self._dev_feat is not None:
            # Device-side featurization owns the hot path: the engine's
            # slow path decodes JSON and predict_async ships raw bytes —
            # the native host tokenize/hash pass this method fronts is the
            # very work the kernel deleted.
            return None
        encode_json = getattr(self.featurizer, "encode_json", None)
        if encode_json is None:
            return None
        pop_ctx = getattr(self.featurizer, "pop_json_splice_ctx", lambda: None)
        is_tree = self._fused_model is None
        tree_binary = is_tree and self._tree_is_binary()
        parts: List[Tuple[object, int]] = []
        stats: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        ctxs: Optional[List[Tuple[object, int]]] = []
        for start in range(0, len(values), self.batch_size):
            chunk = values[start : start + self.batch_size]
            out = encode_json(chunk, text_field,
                              batch_size=self._pad_rows(len(chunk)),
                              keep_splice_ctx=True)
            if out is None:
                return None
            enc, status, span_start, span_len = out
            ctx = pop_ctx()
            if ctx is None:
                ctxs = None
            elif ctxs is not None:
                ctxs.append((ctx, len(chunk)))
            if is_tree:
                parts.append((self._dispatch_tree(enc, tree_binary), len(chunk)))
            else:
                parts.append((self._dispatch_fused(enc), len(chunk)))
            stats.append((status, span_start, span_len))
        pending = PendingPrediction(
            parts,
            threshold=0.5 if is_tree else self._fused_model.threshold,
            argmax=is_tree and not tree_binary)
        if not stats:
            empty = np.empty(0, np.int32)
            return pending, empty, empty, empty, ctxs
        return (pending,
                np.concatenate([s[0] for s in stats]),
                np.concatenate([s[1] for s in stats]),
                np.concatenate([s[2] for s in stats]),
                ctxs)

    def _tree_is_binary(self) -> bool:
        """Binary trees: p(class=1) > 0.5 equals argmax over the normalized
        proba (ties -> class 0 both ways), so a 1-D fetch is exact."""
        return isinstance(self.model, TreeEnsemble) and (
            self.model.kind in ("gbt", "xgboost")  # boosted margins are binary
            or self.model.leaf.shape[-1] == 2)

    def pin_device(self) -> dict:
        """Make every model-side constant device-resident NOW, off the hot
        path: fused LR weights (int8 codes + scale when enabled), tree
        ensemble arrays, and the TF-IDF idf vector. Called once per model
        version — at engine start, at bench warm, and by HotSwapPipeline's
        prewarm so every swap/stage candidate RE-pins before it goes active
        — never per batch. Idempotent per pipeline; returns the pin stats."""
        ds = self.device_stats
        if self._pinned_version is not None:
            return {"pinned_bytes": ds.pinned_bytes, "model_pins": ds.pins}
        arrs = [a for a in jax.tree_util.tree_leaves(
                    self._fused_model if self._fused_model is not None
                    else self.model)
                if isinstance(a, jax.Array)]
        if self._fused_model is None and self._tree_idf is None:
            self._tree_idf = self.featurizer.idf_array()
        if self._tree_idf is not None:
            arrs.append(self._tree_idf)
        if self._q8 is not None:
            arrs.extend(self._q8)
        if self._dev_feat is not None:
            # The stop table is a model-side constant of the device
            # featurize program: uploaded once, pinned with the weights.
            arrs.append(self._dev_feat.stop_table())
        jax.block_until_ready(arrs)
        ds.pinned_bytes = int(sum(a.size * a.dtype.itemsize for a in arrs))
        ds.pins += 1
        self._pinned_version = object()
        return {"pinned_bytes": ds.pinned_bytes, "model_pins": ds.pins}

    def _device_rows(self, ids, counts):
        """Fallback placement for one encoded chunk when the packed staging
        layout doesn't apply (ids widened to int32): two device arrays,
        plain single-chip or row-sharded over the serving mesh's "data"
        axis. The SAME jitted scoring programs serve both — jit follows
        input shardings and GSPMD adds the final gather, so mesh-backed
        streaming (engine -> data-parallel scoring) is a placement decision,
        not a second code path. shard_rows pads rows to a data-axis
        multiple; PendingPrediction already slices every chunk back to its
        real count."""
        ids = np.asarray(ids)
        counts = np.asarray(counts)
        self.device_stats.record_chunk(ids.nbytes + counts.nbytes,
                                       transfers=2, rows=ids.shape[0])
        if self.mesh is None:
            return jnp.asarray(ids), jnp.asarray(counts)
        from fraud_detection_tpu.parallel.mesh import shard_rows

        return shard_rows(ids, self.mesh), shard_rows(counts, self.mesh)

    def _device_packed(self, packed: np.ndarray):
        """Place one packed (B, 2, L) staging buffer: ONE host->device
        transfer per micro-batch chunk (the accounting the bench's
        ``device`` block commits)."""
        self.device_stats.record_chunk(packed.nbytes, transfers=1,
                                       rows=packed.shape[0])
        if self.mesh is None:
            return jnp.asarray(packed)
        from fraud_detection_tpu.parallel.mesh import shard_rows

        return shard_rows(packed, self.mesh)

    def _dispatch_fused(self, enc) -> object:
        """Launch fused sparse LR scoring for one encoded chunk and start the
        async device->host fetch; shared by both predict paths. The chunk
        rides the packed single-buffer upload, donated into the scoring
        program where the platform consumes donations; int8 pipelines score
        through the quantized program on the same staging buffer."""
        packed = _pack_encoded(enc)
        if packed is None:
            ids, counts = self._device_rows(enc.ids, enc.counts)
            p = linear_mod.prob_encoded_arrays(self._fused_model, ids, counts)
        else:
            dev = self._device_packed(packed)
            if self._q8 is not None:
                p = linear_mod.prob_packed_q8(
                    self._q8[0], self._q8[1], self._fused_model.intercept,
                    dev, donate=self._donate)
            else:
                p = linear_mod.prob_packed(self._fused_model, dev,
                                           donate=self._donate)
            if self._donate:
                self.device_stats.donated += 1
        copy_async = getattr(p, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()  # start the device->host fetch behind the dispatch
        return p

    def _dispatch_tree(self, enc, binary: bool) -> object:
        """Launch the scatter-free ensemble traversal for one encoded chunk
        and start the async device->host fetch."""
        if self._tree_idf is None:
            # One upload, reused every chunk (pin_device does this off the
            # hot path; this is the fallback for unpinned pipelines).
            self._tree_idf = self.featurizer.idf_array()
        packed = _pack_encoded(enc)
        if packed is None:
            ids, counts = self._device_rows(enc.ids, enc.counts)
            p = _tree_prob_encoded(self.model, ids, counts, self._tree_idf,
                                   binary)
        else:
            dev = self._device_packed(packed)
            p = _tree_prob_packed(self.model, dev, self._tree_idf, binary,
                                  donate=self._donate)
            if self._donate:
                self.device_stats.donated += 1
        copy_async = getattr(p, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()  # start the device->host fetch behind the dispatch
        return p

    def _dispatch_bytes(self, texts: Sequence[str], rows: int,
                        tree_binary: bool) -> object:
        """Device-featurized dispatch for one chunk: pack raw UTF-8 bytes
        (the host's entire featurize leg — a memcpy), upload the ONE
        staging tensor, and launch the fused featurize+score program. The
        byte tensor is donated where the platform consumes donations, like
        every other staging buffer."""
        dev = self._dev_feat
        staged, truncated = dev.pack(texts, batch_size=rows)
        ds = self.device_stats
        ds.record_featurize(staged.nbytes, len(texts), truncated)
        ds.record_chunk(staged.nbytes, transfers=1, rows=rows)
        if self.mesh is None:
            staged_dev = jnp.asarray(staged)
        else:
            from fraud_detection_tpu.parallel.mesh import shard_rows

            staged_dev = shard_rows(staged, self.mesh)
        stop_tbl = dev.stop_table()
        if self._fused_model is None:
            if self._tree_idf is None:
                self._tree_idf = self.featurizer.idf_array()
            fn = (_tree_prob_bytes_donating if self._donate
                  else _tree_prob_bytes_plain)
            p = fn(self.model, stop_tbl, staged_dev, self._tree_idf,
                   tree_binary, spec=dev.spec)
        elif self._q8 is not None:
            fn = (_prob_bytes_q8_donating if self._donate
                  else _prob_bytes_q8_plain)
            p = fn(self._q8[0], self._q8[1], self._fused_model.intercept,
                   stop_tbl, staged_dev, spec=dev.spec)
        else:
            fn = _prob_bytes_donating if self._donate else _prob_bytes_plain
            p = fn(self._fused_model, stop_tbl, staged_dev, spec=dev.spec)
        if self._donate:
            ds.donated += 1
        copy_async = getattr(p, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()  # start the device->host fetch behind the dispatch
        return p

    def predict_async(self, texts: Sequence[str]) -> "PendingPrediction":
        """Featurize + dispatch device scoring WITHOUT blocking on results.

        Returns a handle whose ``resolve()`` materializes the PredictionBatch.
        JAX dispatch is asynchronous, so the caller can overlap host work
        (decode/produce of neighboring batches) with device execution — the
        lever that hides the per-call device round-trip latency in the
        streaming engine. The host featurize leg itself fans out for large
        chunks: ``featurizer.encode`` shards across the thread pool
        (featurize/parallel.py), so at ``pipeline_depth >= 2`` the engine
        overlaps a PARALLEL featurize with the in-flight batches' device
        wait instead of a single-threaded one."""
        parts: List[Tuple[object, int]] = []
        threshold = 0.5
        argmax = False
        # Multiclass trees need the full (B, C) proba + host argmax — still
        # a single device->host fetch per chunk.
        tree_binary = self._tree_is_binary()
        for start in range(0, len(texts), self.batch_size):
            chunk = list(texts[start : start + self.batch_size])
            n = len(chunk)
            if self._dev_feat is not None:
                # Device-side featurization: raw bytes are the crossing;
                # tokenize/hash/count run inside the scoring program.
                parts.append((self._dispatch_bytes(chunk, self._pad_rows(n),
                                                   tree_binary), n))
                if self._fused_model is not None:
                    threshold = self._fused_model.threshold
                else:
                    argmax = not tree_binary
                continue
            enc = self.featurizer.encode(chunk, batch_size=self._pad_rows(n))
            if self._fused_model is not None:
                parts.append((self._dispatch_fused(enc), n))
                threshold = self._fused_model.threshold
                continue
            # Trees ride the same scatter-free encoded traversal (and packed
            # upload) as the raw-JSON path — the old densify-then-traverse
            # formulation paid a (B, F) XLA scatter plus a second upload
            # per chunk for bit-identical probabilities.
            parts.append((self._dispatch_tree(enc, tree_binary), n))
            argmax = not tree_binary
        return PendingPrediction(parts, threshold=threshold, argmax=argmax)

    def predict(self, texts: Sequence[str]) -> PredictionBatch:
        """Score texts in fixed-size micro-batches (pads the tail batch)."""
        return self.predict_async(texts).resolve()

    def predict_one(self, text: str) -> Tuple[int, float]:
        """Single-dialogue convenience (the reference's per-click path)."""
        batch = self.predict([text])
        return int(batch.labels[0]), float(batch.probabilities[0])

    def predict_encoded(self, ids: np.ndarray,
                        counts: np.ndarray) -> PredictionBatch:
        """Score ALREADY-ENCODED rows: (B, L) hashed feature ids + term
        counts, exactly the packed form the featurizer emits and the learn
        window retains (learn/store.py). The shadow replay path scores a
        staged candidate on the window's rows through this — the rows'
        text was deliberately never kept, and re-featurizing is both
        impossible and unnecessary: padding slots (id 0, count 0) are
        inert on every scoring path, so the stored arrays score exactly
        as the original batch did. Rides the same dispatch entries
        (packed upload, fused LR / encoded tree traversal) as live
        serving; rows chunk and pad to the pipeline's compiled shapes."""
        from fraud_detection_tpu.featurize.tfidf import EncodedBatch

        ids = np.asarray(ids)
        counts = np.asarray(counts)
        if ids.shape != counts.shape or ids.ndim != 2:
            raise ValueError(
                f"ids {ids.shape} / counts {counts.shape} must be equal "
                "2-D (B, L) arrays")
        tree_binary = self._tree_is_binary()
        parts: List[Tuple[object, int]] = []
        threshold = 0.5
        argmax = False
        for start in range(0, ids.shape[0], self.batch_size):
            chunk_ids = ids[start : start + self.batch_size]
            chunk_counts = counts[start : start + self.batch_size]
            n = chunk_ids.shape[0]
            rows = self._pad_rows(n)
            if rows != n:
                chunk_ids = np.concatenate(
                    [chunk_ids, np.zeros((rows - n, ids.shape[1]),
                                         ids.dtype)])
                chunk_counts = np.concatenate(
                    [chunk_counts, np.zeros((rows - n, counts.shape[1]),
                                            counts.dtype)])
            enc = EncodedBatch(ids=chunk_ids, counts=chunk_counts)
            if self._fused_model is not None:
                parts.append((self._dispatch_fused(enc), n))
                threshold = self._fused_model.threshold
            else:
                parts.append((self._dispatch_tree(enc, tree_binary), n))
                argmax = not tree_binary
        return PendingPrediction(parts, threshold=threshold,
                                 argmax=argmax).resolve()


@partial(jax.jit, static_argnames=("binary",))
def _tree_prob_encoded(ensemble: TreeEnsemble, ids, counts, idf, binary: bool):
    """Hashed sparse rows -> scatter-free ensemble traversal, ONE compiled
    program (the tree analogue of linear.prob_encoded, for the raw-JSON fast
    path). The traversal reads each node's split-feature value directly from
    the row's term list (models/trees.py _leaf_indices_encoded) — the old
    densify-then-gather formulation paid a (B, 10000) XLA scatter per chunk,
    the single most expensive op on the tree serving path."""
    proba = trees_mod.predict_proba_encoded(ensemble, ids, counts, idf)
    return proba[:, 1] if binary else proba


def _tree_prob_packed_impl(ensemble: TreeEnsemble, packed, idf, binary: bool):
    ids, counts = linear_mod.unpack_rows(packed)
    proba = trees_mod.predict_proba_encoded(ensemble, ids, counts, idf)
    return proba[:, 1] if binary else proba


_tree_prob_packed_plain = jax.jit(_tree_prob_packed_impl,
                                  static_argnames=("binary",))
_tree_prob_packed_donating = jax.jit(_tree_prob_packed_impl,
                                     static_argnames=("binary",),
                                     donate_argnums=(1,))


def _tree_prob_packed(ensemble: TreeEnsemble, packed, idf, binary: bool,
                      donate: bool = False):
    """Packed-staging-buffer twin of ``_tree_prob_encoded`` (one upload per
    chunk; buffer donated where the platform consumes donations)."""
    fn = _tree_prob_packed_donating if donate else _tree_prob_packed_plain
    return fn(ensemble, packed, idf, binary)


# ---------------------------------------------------------------------------
# Device-side featurization scoring entries (ops/featurize_kernel.py): the
# staging buffer is the raw-byte tensor itself — featurize (Pallas scan +
# count/pack) and scoring fuse into ONE jitted program per model family, so
# bytes -> probability never touches the host in between. Each entry has a
# donating twin for the byte tensor (argument 2 throughout), same policy as
# the packed entries above.
# ---------------------------------------------------------------------------


def _prob_bytes_impl(model: LogisticRegression, stop_tbl, staged, *, spec):
    from fraud_detection_tpu.ops.featurize_kernel import featurize_bytes

    packed, _ = featurize_bytes(staged, stop_tbl, spec=spec)
    ids, counts = linear_mod.unpack_rows(packed)
    gathered = model.weights[ids]
    m = jnp.sum(gathered * counts, axis=-1) + model.intercept
    return jax.nn.sigmoid(m)


_prob_bytes_plain = jax.jit(_prob_bytes_impl, static_argnames=("spec",))
_prob_bytes_donating = jax.jit(_prob_bytes_impl, static_argnames=("spec",),
                               donate_argnums=(2,))


def _prob_bytes_q8_impl(w_q, scales, intercept, stop_tbl, staged, *, spec):
    from fraud_detection_tpu.ops.featurize_kernel import featurize_bytes

    packed, _ = featurize_bytes(staged, stop_tbl, spec=spec)
    return linear_mod._prob_packed_q8_impl(w_q, scales, intercept, packed)


_prob_bytes_q8_plain = jax.jit(_prob_bytes_q8_impl, static_argnames=("spec",))
_prob_bytes_q8_donating = jax.jit(_prob_bytes_q8_impl,
                                  static_argnames=("spec",),
                                  donate_argnums=(4,))


def _tree_prob_bytes_impl(ensemble: TreeEnsemble, stop_tbl, staged, idf,
                          binary: bool, *, spec):
    from fraud_detection_tpu.ops.featurize_kernel import featurize_bytes

    packed, _ = featurize_bytes(staged, stop_tbl, spec=spec)
    return _tree_prob_packed_impl(ensemble, packed, idf, binary)


_tree_prob_bytes_plain = jax.jit(_tree_prob_bytes_impl,
                                 static_argnames=("binary", "spec"))
_tree_prob_bytes_donating = jax.jit(_tree_prob_bytes_impl,
                                    static_argnames=("binary", "spec"),
                                    donate_argnums=(2,))


def synthetic_demo_pipeline(batch_size: int = 256, *, n: int = 800, seed: int = 7,
                            num_features: int = 10000,
                            model: str = "lr",
                            corpus_kwargs: dict | None = None,
                            mesh=None, int8: bool = False,
                            featurize_device=False) -> ServingPipeline:
    """Train a quick model on the synthetic corpus — the shared demo/bench
    fallback pipeline (one recipe, used by bench.py and app/serve.py).
    ``model``: "lr" (default) | "dt" | "rf" | "xgb". ``corpus_kwargs`` is
    forwarded to generate_corpus (e.g. hard_fraction/label_noise=0 for the
    separable corpus transport tests train against)."""
    from fraud_detection_tpu.data import generate_corpus
    from fraud_detection_tpu.models.train_linear import fit_logistic_regression
    from fraud_detection_tpu.models.train_trees import (
        fit_decision_tree, fit_gradient_boosting, fit_random_forest)

    corpus = generate_corpus(n=n, seed=seed, **(corpus_kwargs or {}))
    feat = HashingTfIdfFeaturizer(num_features=num_features)
    feat.fit_idf([d.text for d in corpus])
    X = np.asarray(feat.featurize_dense([d.text for d in corpus]))
    y = np.asarray([d.label for d in corpus], np.float32)
    if model == "lr":
        clf = fit_logistic_regression(X, y, max_iter=50)
    elif model == "dt":
        clf = fit_decision_tree(X, y)
    elif model == "rf":
        clf = fit_random_forest(X, y, n_trees=20)
    elif model == "xgb":
        clf = fit_gradient_boosting(X, y, n_rounds=20)
    else:
        raise ValueError(f"unknown demo model {model!r}")
    return ServingPipeline(feat, clf, batch_size=batch_size, mesh=mesh,
                           int8=int8, featurize_device=featurize_device)
