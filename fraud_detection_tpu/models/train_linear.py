"""Data-parallel logistic-regression training on TPU.

Replaces Spark MLlib's ``LogisticRegression.fit`` (the trainer behind the
shipped artifact's final stage; hyperparameters in its metadata: regParam 0.0,
elasticNetParam 0.0, maxIter 100, tol 1e-6, fitIntercept, standardization).
Optimizer is L-BFGS (optax), full-batch like Spark, with the whole loop under
one jit: ``lax.while_loop`` over L-BFGS updates with gradient-norm + relative
objective-change stopping.

Distribution: rows shard over the mesh "data" axis; the loss is a masked mean,
so XLA inserts the cross-chip psum for the reduction — the moral equivalent of
Spark's treeAggregate over executors (and of XGBoost's Rabit allreduce),
riding ICI instead of the JVM shuffle.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from fraud_detection_tpu.models.linear import LogisticRegression
from fraud_detection_tpu.parallel import mesh as mesh_lib


@dataclass
class FitInfo:
    """Convergence record for a training run."""
    final_loss: float
    iterations: int
    max_iter: int

    @property
    def converged(self) -> bool:
        return self.iterations < self.max_iter


def _loss_fn(params, X, y, mask, l2):
    """Masked mean binary logloss (+ optional L2 on weights, not intercept)."""
    w, b = params
    logits = X @ w + b
    per_row = optax.sigmoid_binary_cross_entropy(logits, y) * mask
    loss = jnp.sum(per_row) / jnp.maximum(jnp.sum(mask), 1.0)
    return loss + 0.5 * l2 * jnp.sum(w * w)


def _fit_lbfgs_impl(X, y, mask, l2, tol, max_iter: int):
    F = X.shape[1]
    params = (jnp.zeros((F,), X.dtype), jnp.zeros((), X.dtype))
    opt = optax.lbfgs()
    state = opt.init(params)
    loss = lambda p: _loss_fn(p, X, y, mask, l2)
    value_and_grad = optax.value_and_grad_from_state(loss)

    def cond(carry):
        params, state, prev_val, it = carry
        val = optax.tree_utils.tree_get(state, "value")
        grad = optax.tree_utils.tree_get(state, "grad")
        tree_norm = getattr(optax.tree_utils, "tree_norm",
                            getattr(optax.tree_utils, "tree_l2_norm", None))
        gnorm = tree_norm(grad)
        rel_impr = jnp.abs(prev_val - val) / jnp.maximum(jnp.abs(prev_val), 1e-12)
        not_converged = jnp.logical_or(it < 2, jnp.logical_and(gnorm > tol, rel_impr > tol))
        return jnp.logical_and(it < max_iter, not_converged)

    def body(carry):
        params, state, _, it = carry
        val, grad = value_and_grad(params, state=state)
        updates, state = opt.update(grad, state, params, value=val, grad=grad, value_fn=loss)
        params = optax.apply_updates(params, updates)
        return params, state, val, it + 1

    init = (params, state, jnp.asarray(jnp.inf, X.dtype), jnp.asarray(0, jnp.int32))
    params, state, _, iters = jax.lax.while_loop(cond, body, init)
    final_loss = loss(params)
    return params, final_loss, iters


# The training matrix is the big buffer (N x F f32 — 800MB at the bench
# shape) and it is dead the moment the fit returns: the donating variant
# hands X/y/mask to XLA at dispatch so their HBM is reclaimable during the
# fit instead of after Python refcounting. Used only where the platform
# consumes donations (models/pipeline.py donation_effective — CPU keeps
# donated buffers and warns, so the plain twin serves there). The old
# ``donate_argnums=()`` here donated nothing; tests/test_train_linear.py
# pins the donating twin's lowering so it can't silently regress to that.
_fit_lbfgs = partial(jax.jit, static_argnames=("max_iter",))(_fit_lbfgs_impl)
_fit_lbfgs_donating = partial(jax.jit, static_argnames=("max_iter",),
                              donate_argnums=(0, 1, 2))(_fit_lbfgs_impl)


def fit_logistic_regression(
    X,
    y,
    *,
    mesh: Optional[Mesh] = None,
    max_iter: int = 100,
    tol: float = 1e-6,
    reg_param: float = 0.0,
    threshold: float = 0.5,
    return_info: bool = False,
) -> Union[LogisticRegression, Tuple[LogisticRegression, FitInfo]]:
    """Fit binary LR on a dense (N, F) feature matrix with labels (N,) in {0,1}.

    With a mesh, rows are padded to a data-axis multiple and sharded (padded
    rows carry mask 0). Returns a ``LogisticRegression`` pytree (float32);
    with ``return_info=True`` also returns a ``FitInfo`` convergence record.
    """
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    if X.ndim != 2 or y.shape != (X.shape[0],):
        raise ValueError(f"bad shapes X{X.shape} y{y.shape}")
    mask = np.ones(X.shape[0], np.float32)
    if mesh is not None:
        Xd = mesh_lib.shard_rows(X, mesh)
        yd = mesh_lib.shard_rows(y, mesh)
        md = mesh_lib.shard_rows(mask, mesh)
    else:
        Xd, yd, md = jnp.asarray(X), jnp.asarray(y), jnp.asarray(mask)
    # Xd/yd/md are fresh uploads owned by this frame — donating them is
    # always safe; the caller's numpy arrays are untouched either way.
    from fraud_detection_tpu.models.pipeline import donation_effective

    fit = _fit_lbfgs_donating if donation_effective() else _fit_lbfgs
    (w, b), final_loss, iters = fit(
        Xd, yd, md, jnp.float32(reg_param), jnp.float32(tol), max_iter)
    model = LogisticRegression(weights=w, intercept=b, threshold=threshold)
    if return_info:
        return model, FitInfo(float(final_loss), int(iters), max_iter)
    return model
