"""On-pod LLM training: resumable AdamW fine-tuning over a (data x model) mesh.

The reference delegates all LLM capability to external services (DeepSeek
HTTPS — /root/reference/utils/agent_api.py:36 — or a local LM Studio server,
deepseek_chat_ui.py:9); it cannot train or adapt the explanation model at
all. This trainer closes that gap for the on-pod path (BASELINE config 5):
fine-tune the JAX decoder (models/llm.py) on explanation transcripts, on the
same pod that serves it.

TPU-first shape:

  * One jitted ``train_step`` — loss, grad, AdamW update under a single jit.
    Batches shard over the mesh "data" axis, parameters keep their Megatron
    tensor-parallel layout over "model" (models/llm.py ``param_shardings``);
    GSPMD inserts the gradient all-reduces over ICI.
  * Optional rematerialization (``remat=True``) wraps the forward in
    ``jax.checkpoint`` — recompute activations in backward instead of storing
    them, the standard HBM-for-FLOPs trade for long-sequence fine-tunes.
  * Document stream -> fixed-shape (B, T+1) windows drawn deterministically
    per step, so every compiled program has one shape and a resumed run sees
    the exact batches the uninterrupted run would have seen.
  * Resume via checkpoint/train_state.py: params + optimizer state + step are
    snapshotted atomically on a cadence; resuming replays nothing and
    continues bit-identically (tests assert array equality).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fraud_detection_tpu.models import llm as llm_mod
from fraud_detection_tpu.models.llm import (
    DATA_AXIS, ByteTokenizer, LanguageModel, Params, TransformerConfig,
    forward, init_params, param_shardings)


@dataclass(frozen=True)
class LLMTrainConfig:
    steps: int = 200
    batch_size: int = 8           # global batch (split over the data axis)
    seq_len: int = 128            # tokens per example (T; windows are T+1)
    learning_rate: float = 3e-4
    warmup_steps: int = 20
    decay_steps: Optional[int] = None  # cosine horizon; defaults to `steps`.
                                  # Set it explicitly when a run may be
                                  # extended: the schedule (not `steps`) is
                                  # what resume must hold fixed, so `steps`
                                  # stays OUT of the snapshot fingerprint.
    weight_decay: float = 0.01
    grad_clip: float = 1.0
    seed: int = 0
    remat: bool = False           # jax.checkpoint the forward (HBM for FLOPs)

    def resolved_decay_steps(self) -> int:
        return self.decay_steps if self.decay_steps is not None else max(
            self.steps, self.warmup_steps + 1)


# ---------------------------------------------------------------------------
# Data: document stream -> deterministic fixed-shape windows
# ---------------------------------------------------------------------------

def pack_corpus(texts: Sequence[str], cfg: TransformerConfig) -> np.ndarray:
    """Byte-tokenize and concatenate the corpus into one token stream with
    BOS/EOS document boundaries (the usual packed-LM layout: no padding, every
    position trains)."""
    tok = ByteTokenizer(cfg)
    parts: List[np.ndarray] = []
    for t in texts:
        ids = tok.encode(t)  # already BOS-prefixed
        parts.append(np.concatenate([ids, [cfg.EOS]]).astype(np.int32))
    stream = np.concatenate(parts) if parts else np.zeros(0, np.int32)
    if stream.size < 2:
        raise ValueError("corpus too small to train on")
    return stream


def batch_for_step(stream: np.ndarray, step: int, tcfg: LLMTrainConfig) -> np.ndarray:
    """(B, T+1) window batch for a step — a pure function of (stream, step,
    seed), so resumed runs draw the exact batches the original would have."""
    rng = np.random.default_rng(np.random.SeedSequence([tcfg.seed, step]))
    span = tcfg.seq_len + 1
    if stream.size < span:
        raise ValueError(
            f"corpus stream ({stream.size} tokens) is smaller than one "
            f"(seq_len + 1 = {span})-token window; shrink seq_len or add data")
    # +1: the last valid start is stream.size - span (inclusive) — dropping it
    # would systematically under-train the corpus tail.
    starts = rng.integers(0, stream.size - span + 1, size=tcfg.batch_size)
    return np.stack([stream[s : s + span] for s in starts])


# ---------------------------------------------------------------------------
# The jitted step
# ---------------------------------------------------------------------------

def _loss_fn(params: Params, windows: jax.Array, cfg: TransformerConfig,
             remat: bool, seq_mesh=None) -> jax.Array:
    """Mean next-token cross-entropy over (B, T+1) windows."""
    # use_flash=False: training runs params model-axis sharded (dp x tp) and
    # pallas_call has no GSPMD partitioning rule (llm.causal_attention).
    # seq_mesh: sequence-parallel training — the forward's attention rides
    # the ring over the mesh "seq" axis (gradients flow back through the
    # ppermute rotation). Bound via partial so jax.checkpoint never traces
    # either flag.
    fwd = partial(forward, use_flash=False, seq_mesh=seq_mesh)
    if remat:
        fwd = jax.checkpoint(fwd, static_argnums=(2,))
    logits, _ = fwd(params, windows[:, :-1], cfg)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    tgt = windows[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def make_optimizer(tcfg: LLMTrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=tcfg.learning_rate,
        warmup_steps=tcfg.warmup_steps,
        decay_steps=tcfg.resolved_decay_steps())
    return optax.chain(
        optax.clip_by_global_norm(tcfg.grad_clip),
        optax.adamw(schedule, weight_decay=tcfg.weight_decay))


@partial(jax.jit, static_argnames=("cfg", "tcfg", "opt", "seq_mesh"))
def _train_step(params: Params, opt_state, windows: jax.Array,
                cfg: TransformerConfig, tcfg: LLMTrainConfig,
                opt: optax.GradientTransformation, seq_mesh=None):
    loss, grads = jax.value_and_grad(_loss_fn)(params, windows, cfg,
                                               tcfg.remat, seq_mesh)
    updates, opt_state = opt.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


# ---------------------------------------------------------------------------
# Checkpoint plumbing: pytree <-> flat npz arrays
# ---------------------------------------------------------------------------

def _flatten_state(params: Params, opt_state) -> Dict[str, np.ndarray]:
    arrays = {f"params.{k}": np.asarray(v) for k, v in params.items()}
    leaves = jax.tree_util.tree_leaves(opt_state)
    for i, leaf in enumerate(leaves):
        arrays[f"opt.{i:04d}"] = np.asarray(leaf)
    return arrays


def _unflatten_state(arrays: Dict[str, np.ndarray], params_like: Params,
                     opt_state_like) -> Tuple[Params, object]:
    params = {k: jnp.asarray(arrays[f"params.{k}"]).astype(v.dtype)
              for k, v in params_like.items()}
    treedef = jax.tree_util.tree_structure(opt_state_like)
    n = len(jax.tree_util.tree_leaves(opt_state_like))
    leaves = [jnp.asarray(arrays[f"opt.{i:04d}"]) for i in range(n)]
    return params, jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# Public trainer
# ---------------------------------------------------------------------------

def fit_language_model(
    texts: Sequence[str],
    cfg: Optional[TransformerConfig] = None,
    tcfg: Optional[LLMTrainConfig] = None,
    *,
    mesh: Optional[Mesh] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 50,
    log_every: int = 0,
) -> Tuple[LanguageModel, List[float]]:
    """Fine-tune the byte-level decoder on a text corpus.

    With ``mesh`` (axes ``("data",)``, ``("data", "model")``, or
    ``("data", "seq")``), batches shard over "data", parameters
    tensor-parallel over "model", and attention sequence-parallel over
    "seq" (ring attention in the training step — gradients flow through
    the ppermute rotation) — the layouts an on-pod explanation model
    trains with. Returns the trained
    ``LanguageModel`` and the per-step loss history of THIS invocation.
    """
    cfg = cfg or TransformerConfig()
    tcfg = tcfg or LLMTrainConfig()
    if checkpoint_dir is not None and checkpoint_every < 1:
        raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
    stream = pack_corpus(texts, cfg)
    opt = make_optimizer(tcfg)

    params = init_params(jax.random.PRNGKey(tcfg.seed), cfg)
    if mesh is not None and llm_mod.MODEL_AXIS in mesh.axis_names:
        sh = param_shardings(cfg, mesh)
        params = {k: jax.device_put(v, sh[k]) for k, v in params.items()}
    # flightcheck: ignore[FC201] — once per training run (device placement of the fresh opt state)
    opt_state = jax.jit(opt.init)(params)

    fingerprint = None
    start_step = 0
    if checkpoint_dir is not None:
        from fraud_detection_tpu.checkpoint import train_state as ts

        import hashlib

        # `steps` is the run length, not the setup: extending a run must
        # resume, so it stays out. The RESOLVED schedule horizon is what must
        # match (an uninterrupted long run and a resumed one see the same LR
        # at every step index).
        tc = {k: (int(v) if isinstance(v, bool) else v)
              for k, v in sorted(tcfg.__dict__.items())
              if k not in ("steps", "decay_steps")}
        tc["resolved_decay_steps"] = tcfg.resolved_decay_steps()
        fingerprint = {
            "config": {k: str(v) for k, v in sorted(cfg.__dict__.items())},
            "train_config": tc,
            "stream_sha256": hashlib.sha256(stream.tobytes()).hexdigest(),
        }
        fingerprint.update(ts.mesh_extra(mesh))
        snap = ts.load_for(checkpoint_dir, "language_model", fingerprint)
        if snap is not None:
            start_step, arrays = snap
            if start_step > tcfg.steps:
                # Unlike boosting (where extra trees can be truncated), AdamW
                # state cannot be rolled back; clamping would silently return
                # an over-trained model for a shorter request.
                raise ValueError(
                    f"snapshot in {checkpoint_dir} has already trained "
                    f"{start_step} steps but steps={tcfg.steps} was requested; "
                    "raise steps to extend the run or delete the snapshot to "
                    "retrain from scratch")
            loaded_params, loaded_opt = _unflatten_state(arrays, params, opt_state)
            # Re-place BOTH trees with the shardings of their freshly
            # initialized counterparts (params TP-sharded, AdamW moments
            # following them): host-loaded arrays fed unplaced into the jit
            # would recompile and, on multi-host meshes, fail outright.
            params = jax.tree_util.tree_map(
                lambda loaded, like: jax.device_put(loaded, like.sharding),
                loaded_params, params)
            opt_state = jax.tree_util.tree_map(
                lambda loaded, like: jax.device_put(loaded, like.sharding),
                loaded_opt, opt_state)

    batch_sharding = None
    if mesh is not None and DATA_AXIS in mesh.axis_names:
        if tcfg.batch_size % mesh.shape[DATA_AXIS] != 0:
            raise ValueError(
                f"batch_size {tcfg.batch_size} not divisible by data axis "
                f"size {mesh.shape[DATA_AXIS]}")
        batch_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    # Sequence parallelism: attention rides the ring over "seq" (dp x sp —
    # long-transcript fine-tuning where a single device can't hold T).
    seq_mesh = None
    if mesh is not None and llm_mod.SEQ_AXIS in mesh.axis_names:
        if tcfg.seq_len % mesh.shape[llm_mod.SEQ_AXIS] != 0:
            raise ValueError(
                f"seq_len {tcfg.seq_len} not divisible by seq axis "
                f"size {mesh.shape[llm_mod.SEQ_AXIS]}")
        seq_mesh = mesh

    losses: List[float] = []
    for step in range(start_step, tcfg.steps):
        windows = jnp.asarray(batch_for_step(stream, step, tcfg))
        if batch_sharding is not None:
            windows = jax.device_put(windows, batch_sharding)
        params, opt_state, loss = _train_step(
            params, opt_state, windows, cfg, tcfg, opt, seq_mesh)
        losses.append(float(loss))
        if log_every and (step + 1) % log_every == 0:
            print(f"step {step + 1}/{tcfg.steps} loss {losses[-1]:.4f}")
        if checkpoint_dir is not None and (
                (step + 1) % checkpoint_every == 0 or step + 1 == tcfg.steps):
            ts.save_train_state(
                checkpoint_dir, "language_model", step + 1, fingerprint,
                _flatten_state(params, opt_state))

    return LanguageModel(cfg, params), losses
