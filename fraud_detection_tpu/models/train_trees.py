"""Histogram-based tree training, TPU-native.

Replaces the reference's three tree trainers — Spark MLlib DecisionTree
(maxDepth=5, gini), RandomForest (100 trees, depth 5, featureSubsetStrategy
"auto") and SparkXGBClassifier (100 rounds, depth 5, second-order boosting
with Rabit allreduce) — fraud_detection_spark.py:56-91 — with one engine:

  * Features are quantile-binned once (Spark's own maxBins=32 discretization).
  * Trees grow level-wise in heap layout (node i -> children 2i+1, 2i+2) with
    a FIXED depth bound, so the entire builder is one jit: per level, a
    per-(node, feature, bin) statistics histogram via segment-sum, a cumsum
    gain scan over bins, and a masked argmax pick the splits; rows then
    re-route by gathering their node's split. No data-dependent control flow
    anywhere — XLA sees dense scatter/cumsum/argmax over static shapes.
  * Split criteria are pluggable over the same histograms: weighted-gini
    impurity decrease (Spark DT/RF semantics) and second-order logloss gain
    (XGBoost semantics: G^2/(H+lambda) with leaf value -G/(H+lambda)).
  * Random forest = the same builder looped per chunk inside one program
    over Poisson(1) bootstrap row weights with per-node Bernoulli feature
    masks (expected size sqrt(F), approximating Spark's exact sqrt subset -
    documented deviation).
  * Boosting = the builder called per round on (grad, hess) stats.

On single-TPU runs the per-level histogram and gain scan default to the
Pallas MXU kernels (ops/histogram.py); trainer loops keep per-round state on
device so wall-clock is not dominated by host round-trips.

Distribution: with inputs sharded over the mesh "data" axis, the per-level
segment-sums reduce across chips (XLA inserts the psum) — exactly the
gradient-histogram allreduce XGBoost does over Rabit, riding ICI instead
(the Pallas path is forced off under a mesh: pallas_call has no SPMD
partitioning rule — see resolve_config).
"""

from __future__ import annotations

import math
import weakref
from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fraud_detection_tpu.models.trees import TreeEnsemble


# ---------------------------------------------------------------------------
# Quantile binning
# ---------------------------------------------------------------------------

def quantile_bin_edges(X: np.ndarray, n_bins: int = 32) -> np.ndarray:
    """Per-feature quantile edges, (F, n_bins - 1), host-side numpy.

    Mirrors Spark's maxBins quantile discretization. Duplicate edges (heavy
    zero-inflation in TF-IDF columns) are fine: bins collapse and those split
    candidates simply tie.
    """
    qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return np.quantile(np.asarray(X, np.float32), qs, axis=0).T.astype(np.float32)


def bin_rows_host(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Host-side twin of ``apply_bins`` returning int8 bin ids.

    bin = #(edges < x) for both (``searchsorted(..., side="left")`` counts
    strictly-smaller sorted edges), so uploading these bins and training on
    them is bit-identical to uploading floats and binning on device — at a
    quarter of the bytes (int8 vs f32), which matters when the device link is
    a remote tunnel (round-2 verdict: the 100k x 2048 f32 upload dwarfed
    every fit it fed). n_bins <= 128 keeps int8 exact; the trainers widen to
    int32 on device."""
    if edges.shape[1] > 127:
        raise ValueError(
            f"{edges.shape[1]} edges per feature exceeds int8 range "
            "(n_bins must be <= 128 for host binning)")
    if not np.isfinite(X).all():
        # searchsorted sorts NaN above every edge (top bin) while apply_bins
        # counts `edges < NaN` as 0 (bottom bin) — refuse rather than let
        # the two documented-equivalent paths train different models.
        raise ValueError("bin_rows_host requires finite input "
                         "(NaN/inf bin differently on host and device)")
    out = np.empty(X.shape, np.int8)
    for f in range(X.shape[1]):
        out[:, f] = np.searchsorted(edges[f], X[:, f], side="left")
    return out


@jax.jit
def apply_bins(X: jax.Array, edges: jax.Array) -> jax.Array:
    """(N, F) values -> (N, F) int32 bin ids; bin = #(edges < x) so that
    ``x <= edges[b]  <=>  bin(x) <= b`` (keeps serve-time ``x <= threshold``
    traversal bit-consistent with train-time binning).

    Computed as an unrolled compare-accumulate over the (static, <= 31) edge
    columns rather than a binary search: ``searchsorted``'s data-dependent
    gathers are hostile to the VPU (seconds at 100k x 2048 on TPU), while
    the compares fuse into one elementwise HBM sweep."""
    bins = jnp.zeros(X.shape, jnp.int32)
    for j in range(edges.shape[1]):
        bins = bins + (X > edges[None, :, j]).astype(jnp.int32)
    return bins


# ---------------------------------------------------------------------------
# Split criteria over (left, right) stat blocks
# ---------------------------------------------------------------------------

def _gini_impurity(stats: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """stats (..., K) class counts -> (impurity, total_count)."""
    n = stats.sum(-1)
    p = stats / jnp.maximum(n[..., None], 1e-12)
    return 1.0 - jnp.sum(p * p, axis=-1), n


def _gini_gain(left: jax.Array, total: jax.Array) -> jax.Array:
    """Weighted impurity decrease for every (node, feature, bin) candidate.

    left: (L, F, B, K) cumulative class counts for rows with bin <= b;
    total: (L, 1, 1, K). Returns (L, F, B) gain; empty-child candidates -inf.
    """
    right = total - left
    gi_p, n_p = _gini_impurity(total)
    gi_l, n_l = _gini_impurity(left)
    gi_r, n_r = _gini_impurity(right)
    n_safe = jnp.maximum(n_p, 1e-12)
    gain = gi_p - (n_l * gi_l + n_r * gi_r) / n_safe
    valid = (n_l > 0) & (n_r > 0)
    return jnp.where(valid, gain, -jnp.inf)


def _xgb_gain(left: jax.Array, total: jax.Array, lam: float, min_child_weight: float) -> jax.Array:
    """Second-order gain: stats K=3 are (grad, hess, count)."""
    right = total - left
    gl, hl = left[..., 0], left[..., 1]
    gr, hr = right[..., 0], right[..., 1]
    gp, hp = total[..., 0], total[..., 1]
    score = lambda g, h: (g * g) / (h + lam)
    gain = 0.5 * (score(gl, hl) + score(gr, hr) - score(gp, hp))
    valid = (hl >= min_child_weight) & (hr >= min_child_weight) & \
            (left[..., 2] > 0) & (right[..., 2] > 0)
    return jnp.where(valid, gain, -jnp.inf)




def _feature_mask(mask_keys_level, width: int, f: int, f_padded: int):
    """Per-node Bernoulli feature subsets (expected size sqrt(F)), batched
    over a leading tree axis: mask_keys_level (T, key) -> (T, width, f_padded).

    The draw runs over the TRUE feature count ``f`` (the subset probability
    and the PRNG stream must not depend on tile-alignment padding); padded
    feature columns are masked False so they can never be selected."""
    p_keep = jnp.sqrt(jnp.float32(f)) / f
    mask = jax.vmap(
        lambda key: jax.random.bernoulli(key, p_keep, (width, f))
    )(mask_keys_level)
    # Bias-free fallback: a node that drew an empty subset (probability
    # ~(1-p)^F, astronomically rare) considers all features.
    empty = ~mask.any(axis=2)
    mask = mask | empty[:, :, None]
    if f_padded != f:
        mask = jnp.pad(mask, ((0, 0), (0, 0), (0, f_padded - f)))
    return mask



def _node_totals(stats, seg_node, width: int, batch_factor: int = 1):
    """Per-node stat totals as a one-hot matmul instead of segment_sum:
    XLA lowers segment_sum to a serial scatter-add (~10ms for 100k rows on
    TPU) while the (L+1, N) @ (N, K) contraction is trivial MXU work.
    HIGHEST precision keeps f32-faithful accumulation: exact for the integer
    gini stats, ulp-level for xgb grad/hess. The overflow segment (rows with
    seg_node == width) is computed and sliced away, same as the scatter
    formulation.

    The dense one-hot transient is (width+1, N) f32 — fine at the default
    depth 5 (width <= 32) but growing as 2^depth * N; above a ~256MB
    threshold (e.g. depth 10 at 1M rows would be ~4GB) this falls back to
    the segment_sum formulation it replaced, trading the MXU win for
    bounded memory. ``batch_factor``: callers that vmap this over a tree
    chunk pass the chunk width so the threshold sees the REAL materialized
    size (T, width+1, N), not the per-tree slice."""
    n = stats.shape[0]
    if batch_factor * (width + 1) * n * 4 > _DENSE_TRANSIENT_LIMIT:
        return jax.ops.segment_sum(stats, seg_node, num_segments=width + 1)[:-1]
    onehot = (seg_node[None, :] == jnp.arange(width + 1)[:, None]).astype(
        stats.dtype)                                       # (L+1, N)
    return jax.lax.dot_general(
        onehot, stats, (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)[:-1]          # (L, K)


def _child_totals(hist, totals, best_f, best_b, do_split):
    """Next level's per-node totals from this level's histogram: the left
    child's stats are the cumulative histogram of the parent's chosen
    feature at the chosen bin; the right child's are the complement. Heap
    order interleaves (left, right) per parent; children of non-split
    parents get zeros (no rows ever route there — matches the scanned
    totals). Supports an optional leading tree axis.

    hist (..., L, F, NB, K); totals (..., L, K); best_f/best_b/do_split
    (..., L) -> (..., 2L, K)."""
    # One-hot contractions instead of take_along_axis: TPU lowers these
    # small-table gathers to kCustom scans over the full (.., L, F, NB, K)
    # slab (~6ms/level profiled); the masked reductions are single
    # vectorized passes.
    f = hist.shape[-3]
    onehot_f = (best_f[..., None] == jnp.arange(f)).astype(hist.dtype)
    hist_f = jnp.einsum("...lfbk,...lf->...lbk", hist, onehot_f,
                        precision=jax.lax.Precision.HIGHEST)
    cum_f = jnp.cumsum(hist_f, axis=-2)
    nb = hist.shape[-2]
    onehot_b = (best_b[..., None] == jnp.arange(nb)).astype(hist.dtype)
    left = jnp.einsum("...lbk,...lb->...lk", cum_f, onehot_b,
                      precision=jax.lax.Precision.HIGHEST)   # (..., L, K)
    right = totals - left
    pair = jnp.stack([left, right], axis=-2)              # (..., L, 2, K)
    pair = pair * do_split[..., None, None]
    shape = pair.shape[:-3] + (2 * pair.shape[-3], pair.shape[-1])
    return pair.reshape(shape)


def _select_splits(hist, totals, mask, cfg: TreeTrainConfig):
    """XLA split selection for one level, batched over a leading tree axis.

    hist (T, L, F, NB, K) statistics; totals (T, L, K); mask (T, L, F) bool
    feature subsets or None. Returns (best_f, best_b, best_gain), each
    (T, L) — flat first-occurrence argmax over (F, NB-1) per node.
    """
    nb = cfg.n_bins
    # Inclusive bin prefix as an upper-triangular matmul: jnp.cumsum lowers
    # to a log-step scan (~log2(NB) full passes over the (T, L, F, NB, K)
    # slab per level), while the (NB, NB) contraction is one MXU pass —
    # the same formulation the Pallas gain kernel uses in-tile. HIGHEST
    # precision keeps the f32 count/grad accumulation exact at these
    # magnitudes (a default bf16 dot would round counts above 2^8).
    tri = (jnp.arange(nb)[:, None] <= jnp.arange(nb)[None, :]).astype(hist.dtype)
    cum = jnp.einsum("tlfbk,bc->tlfck", hist, tri,
                     precision=jax.lax.Precision.HIGHEST)
    total_b = totals[:, :, None, None, :]
    if cfg.criterion == "gini":
        gain = _gini_gain(cum, total_b)                   # (T, L, F, NB)
    else:
        gain = _xgb_gain(cum, total_b, cfg.reg_lambda, cfg.min_child_weight)
    gain = gain[..., : nb - 1]                            # last bin: no right side
    if mask is not None:
        gain = jnp.where(mask[:, :, :, None], gain, -jnp.inf)
    t, width = gain.shape[:2]
    flat = gain.reshape(t, width, -1)
    best = jnp.argmax(flat, axis=2)
    best_gain = jnp.take_along_axis(flat, best[:, :, None], axis=2)[:, :, 0]
    return ((best // (nb - 1)).astype(jnp.int32),
            (best % (nb - 1)).astype(jnp.int32), best_gain)


#: Dense-transient budget shared by _route_rows and _node_totals guards.
_DENSE_TRANSIENT_LIMIT = 256 * 1024 * 1024


def _route_rows(bins, local, seg_valid, node, best_f, best_b, do_split,
                width: int, dense_limit: int = _DENSE_TRANSIENT_LIMIT):
    """Row re-routing for one level, batched over a leading tree axis:
    gather each row's node's chosen split, compare bin ids, descend.
    Rows whose node became a leaf stop descending and drop out of deeper
    histograms (their prediction lives at the marked leaf).
    local/seg_valid/node (T, N); best_f/best_b/do_split (T, L).
    Returns (node, active), each (T, N)."""
    row_local = jnp.clip(local, 0, width - 1)
    # Per-NODE column extraction instead of a per-row feature gather: every
    # row at node l reads the same split column best_f[t, l], so ONE
    # (N, F) @ (F, T*L) one-hot matmul pulls all needed bin columns (exact:
    # bin ids < 32 are exact in bf16 operands / f32 accumulation) and a
    # vectorized one-hot select picks each row's own node column. The
    # row-wise take_along_axis this replaces lowered to a serialized TPU
    # gather — ~25ms per level at bench shape, the forest builder's single
    # largest op (profiled r5); the matmul reads bins once at ~1ms.
    t, n = local.shape
    if t * n * width * 4 > dense_limit:
        # Same 256MB dense-transient guard as _node_totals: deep/wide
        # configs fall back to the row-wise gathers (slower, O(T*N) memory —
        # no (T, N, width) one-hot anywhere on this branch).
        row_b = jnp.take_along_axis(best_b, row_local, axis=1)
        row_split = jnp.take_along_axis(do_split, row_local, axis=1)
        row_f = jnp.take_along_axis(best_f, row_local, axis=1)
        row_bin = jax.vmap(
            lambda rf: jnp.take_along_axis(bins, rf[:, None], axis=1)[:, 0]
        )(row_f).astype(jnp.float32)
    else:
        # sel: each row's one-hot over this level's nodes — drives the
        # per-node column select AND the small-table lookups (row_b,
        # row_split), which as take_along_axis lowered to ~5ms kCustom
        # gathers over (T, N) on TPU (profiled r5).
        sel = row_local[:, :, None] == jnp.arange(width)[None, None, :]
        row_b = jnp.sum(jnp.where(sel, best_b[:, None, :], 0), axis=2)
        row_split = jnp.any(sel & do_split[:, None, :], axis=2)
        f = bins.shape[1]
        onehot_f = (best_f.reshape(-1)[None, :]
                    == jnp.arange(f)[:, None]).astype(jnp.bfloat16)  # (F, T*L)
        cols = jax.lax.dot_general(
            bins.astype(jnp.bfloat16), onehot_f, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                      # (N, T*L)
        cols = cols.reshape(n, *best_f.shape).transpose(1, 0, 2)
        row_bin = jnp.sum(jnp.where(sel, cols, 0.0), axis=2)         # (T, N)
    go_left = row_bin <= row_b.astype(row_bin.dtype)
    new_node = jnp.where(go_left, 2 * node + 1, 2 * node + 2)
    node = jnp.where(seg_valid & row_split, new_node, node)
    return node, seg_valid & row_split


# ---------------------------------------------------------------------------
# Single-tree level-wise builder (jit-unrolled over levels)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TreeTrainConfig:
    max_depth: int = 5            # Spark maxDepth=5 (fraud_detection_spark.py:62,72,81)
    n_bins: int = 32              # Spark default maxBins
    min_info_gain: float = 0.0
    criterion: str = "gini"       # "gini" | "xgb"
    reg_lambda: float = 1.0       # xgb: L2 on leaf values and split gain
    min_child_weight: float = 1e-6
    learning_rate: float = 0.3    # xgb: leaf-value shrinkage (eta)
    # Pallas histogram + gain-scan kernels (ops/histogram.py) for the
    # no-feature-mask path (DT/boosting). None = auto: compiled kernels on
    # TPU, XLA segment-sum elsewhere (interpret-mode Pallas is only for
    # tests). Resolved to a concrete bool at construction so jit static
    # hashing and resume fingerprints see a deterministic value.
    use_pallas: Optional[bool] = None

    def __post_init__(self):
        if self.use_pallas is None:
            object.__setattr__(self, "use_pallas",
                               jax.default_backend() == "tpu")


def _build_tree(bins, stats, row_weights, feature_mask_keys, cfg: TreeTrainConfig,
                true_features: Optional[int] = None):
    """Grow one tree. All shapes static; python loop over levels unrolls in jit.

    bins: (N, F) int32; stats: (N, K) per-row statistics (class one-hots for
    gini; grad/hess/count for xgb), already multiplied by bootstrap weights;
    row_weights: (N,) 0/1-ish activity weights; feature_mask_keys: PRNG key
    per level for Bernoulli feature subsets, or None for all features.

    ``true_features``: the pre-padding feature count — the Bernoulli
    feature-subset draw must not see tile-alignment padding (subset
    probability and PRNG stream follow the real F).

    Returns flat arrays (M,) feature/threshold-bin/left/right + (M, K) stats.
    """
    n, f = bins.shape
    f_true = f if true_features is None else true_features
    k = stats.shape[-1]
    nb = cfg.n_bins
    depth = cfg.max_depth
    m = 2 ** (depth + 1) - 1

    feature = jnp.full((m,), -1, jnp.int32)
    split_bin = jnp.zeros((m,), jnp.int32)
    left_child = jnp.full((m,), -1, jnp.int32)
    right_child = jnp.full((m,), -1, jnp.int32)
    node_stats = jnp.zeros((m, k), stats.dtype)

    stats = stats * row_weights[:, None]
    node = jnp.zeros((n,), jnp.int32)  # heap position per row
    active = row_weights > 0

    # Gini statistics are one-hot class counts times small-integer weights:
    # the histogram runs as ONE exact int8 MXU pass (vs two bf16 passes for
    # float grad/hess stats), and node totals are DERIVED instead of scanned:
    # level 0's from the histogram (feature 0's bins partition the root's
    # rows), deeper levels' from the parent's cumulative stats at its chosen
    # split (left child = cum[f*, b*]; right = parent - left) — sibling
    # arithmetic that removes every per-level segment-sum sweep including
    # the leaf level's. All quantities are exact integers, so the derived
    # totals are bit-equal to the XLA path's scanned ones.
    exact = bool(cfg.use_pallas) and cfg.criterion == "gini"
    carried = None   # exact path: totals for this level, derived at l-1

    for level in range(depth + 1):
        offset = 2 ** level - 1
        width = 2 ** level
        local = node - offset
        seg_valid = active & (local >= 0) & (local < width)
        # Inactive rows route to an overflow segment that is sliced away.
        seg_node = jnp.where(seg_valid, local, width)

        if level == depth:
            # Deepest level grows no splits: only the leaf totals are needed
            # — derived on the exact path, one cheap scan on the float path.
            totals = (carried if exact and carried is not None
                      else _node_totals(stats, seg_node, width))
            node_stats = node_stats.at[offset : offset + width].set(totals)
            break

        if cfg.use_pallas:
            # The Pallas MXU histogram serves every trainer — feature masks
            # only affect SPLIT SELECTION, not the statistics, so the forest
            # path reuses the same kernel and applies its mask on the gains.
            from fraud_detection_tpu.ops.histogram import (
                auto_interpret, best_splits, node_feature_bin_histogram)

            hist = node_feature_bin_histogram(
                bins, jnp.where(seg_valid, local, width), stats,
                n_nodes=width, n_bins=nb, interpret=auto_interpret(),
                exact_int8=exact)
        else:
            def hist_one_feature(fbins):
                seg = jnp.where(seg_valid, local * nb + fbins, width * nb)
                return jax.ops.segment_sum(stats, seg, num_segments=width * nb + 1)[:-1]
            hist = jax.vmap(hist_one_feature, in_axes=1)(bins)      # (F, L*NB, K)
            hist = hist.reshape(f, width, nb, k).transpose(1, 0, 2, 3)  # (L,F,NB,K)

        if exact:
            totals = (hist[:, 0].sum(axis=1) if carried is None else carried)
        else:
            totals = _node_totals(stats, seg_node, width)
        node_stats = node_stats.at[offset : offset + width].set(totals)

        if cfg.use_pallas and feature_mask_keys is None:
            best_f, best_b, best_gain = best_splits(
                hist, totals, criterion=cfg.criterion, n_bins=nb,
                reg_lambda=cfg.reg_lambda, min_child_weight=cfg.min_child_weight,
                interpret=auto_interpret())
        else:
            mask = (None if feature_mask_keys is None
                    else _feature_mask(feature_mask_keys[level][None], width,
                                       f_true, f))
            bf, bb, bg = _select_splits(hist[None], totals[None], mask, cfg)
            best_f, best_b, best_gain = bf[0], bb[0], bg[0]
        do_split = best_gain > cfg.min_info_gain

        pos = offset + jnp.arange(width)
        feature = feature.at[pos].set(jnp.where(do_split, best_f, -1))
        split_bin = split_bin.at[pos].set(best_b)
        left_child = left_child.at[pos].set(jnp.where(do_split, 2 * pos + 1, -1))
        right_child = right_child.at[pos].set(jnp.where(do_split, 2 * pos + 2, -1))

        if exact:
            carried = _child_totals(hist, totals, best_f, best_b, do_split)

        node1, active1 = _route_rows(
            bins, local[None], seg_valid[None], node[None],
            best_f[None], best_b[None], do_split[None], width)
        node, active = node1[0], active1[0]

    # ``node`` is each ACTIVE row's final leaf heap position — the boosting
    # round reuses it instead of re-traversing (a per-row gather walk).
    # Weight-0 rows (tile padding, mesh padding) never route and stay at 0;
    # their margins are inert (stats are weight-zeroed before every
    # histogram), so this costs nothing downstream.
    return feature, split_bin, left_child, right_child, node_stats, node


@partial(jax.jit, static_argnames=("cfg", "use_feature_mask", "true_features"))
def _build_tree_jit(bins, stats, row_weights, mask_keys, cfg: TreeTrainConfig,
                    use_feature_mask: bool, true_features: Optional[int] = None):
    keys = mask_keys if use_feature_mask else None
    return _build_tree(bins, stats, row_weights, keys, cfg, true_features)[:5]


@partial(jax.jit, static_argnames=("cfg", "use_feature_mask", "true_features"))
def _build_tree_chunk(bins, stats, row_weights, mask_keys,
                      cfg: TreeTrainConfig, use_feature_mask: bool,
                      true_features: Optional[int] = None):
    """A chunk of independent trees in ONE program.

    Pallas path: all trees per level go through ONE fused multi-tree
    histogram kernel — the trees share ``bins``, so the kernel's dominant
    cost (the multihot build) is paid once per cell instead of per tree, and
    the fused dot fills MXU lanes a single tree leaves idle.

    XLA path: looped (not vmapped) single-tree builds — vmapping the
    segment-sum histogram multiplies its working set by the chunk size and
    OOMs HBM at bench scale.

    Per-tree PRNG keys come from the caller, so the chunking strategy never
    changes results."""
    if cfg.use_pallas:
        return _build_forest_chunk_pallas(
            bins, stats, row_weights,
            mask_keys if use_feature_mask else None, cfg, true_features)
    outs = [
        _build_tree(bins, stats, row_weights[i],
                    mask_keys[i] if use_feature_mask else None, cfg,
                    true_features)[:5]     # drop the per-row leaf positions
        for i in range(row_weights.shape[0])
    ]
    return tuple(jnp.stack(parts) for parts in zip(*outs))


def _build_forest_chunk_pallas(bins, stats, row_weights, mask_keys,
                               cfg: TreeTrainConfig,
                               true_features: Optional[int] = None):
    """Batched level-wise builder: every per-row/per-node array carries a
    leading tree axis, and the per-level histogram is one
    ``node_feature_bin_histogram_multi`` call for the whole chunk. Math is
    identical to looping ``_build_tree`` per tree (same per-element f32
    products, same hi/lo bf16 rounding, same masked-gain argmaxes) — the
    interpret-mode parity test asserts structural equality."""
    from fraud_detection_tpu.ops.histogram import (
        auto_interpret, node_feature_bin_histogram_multi)

    t, n = row_weights.shape
    f = bins.shape[1]
    k = stats.shape[-1]
    nb = cfg.n_bins
    depth = cfg.max_depth
    m = 2 ** (depth + 1) - 1

    feature = jnp.full((t, m), -1, jnp.int32)
    split_bin = jnp.zeros((t, m), jnp.int32)
    left_child = jnp.full((t, m), -1, jnp.int32)
    right_child = jnp.full((t, m), -1, jnp.int32)
    node_stats = jnp.zeros((t, m, k), stats.dtype)

    node = jnp.zeros((t, n), jnp.int32)
    active = row_weights > 0
    # Gini chunks (the forest's only criterion) qualify for the exact int8
    # MXU pass: one-hot class stats x Poisson weights, products < 128.
    exact = cfg.criterion == "gini"

    def seg_totals(locals_masked, width):
        # per-tree totals via the one-hot matmul (segment_sum scatters are
        # ~10ms per call at bench scale; this is trivial MXU work)
        return jax.vmap(
            lambda loc, w: _node_totals(stats * w[:, None], loc, width,
                                        batch_factor=t)
        )(locals_masked, row_weights)                           # (T, L, K)

    carried = None   # exact path: this level's totals, derived at l-1

    for level in range(depth + 1):
        offset = 2 ** level - 1
        width = 2 ** level
        local = node - offset                                   # (T, N)
        seg_valid = active & (local >= 0) & (local < width)
        locals_masked = jnp.where(seg_valid, local, width)

        if level == depth:
            # Leaves only: derived totals (exact path) skip the final scan.
            totals = (carried if exact and carried is not None
                      else seg_totals(locals_masked, width))
            node_stats = node_stats.at[:, offset : offset + width].set(totals)
            break

        hist = node_feature_bin_histogram_multi(
            bins, locals_masked, row_weights, stats,
            n_nodes=width, n_bins=nb, interpret=auto_interpret(),
            exact_int8=exact)
        if exact:
            totals = (hist[:, :, 0].sum(axis=2) if carried is None
                      else carried)                             # (T, L, K)
        else:
            totals = seg_totals(locals_masked, width)
        node_stats = node_stats.at[:, offset : offset + width].set(totals)

        mask = (None if mask_keys is None
                else _feature_mask(mask_keys[:, level], width,
                                   f if true_features is None else true_features,
                                   f))
        best_f, best_b, best_gain = _select_splits(hist, totals, mask, cfg)
        do_split = best_gain > cfg.min_info_gain

        pos = offset + jnp.arange(width)
        feature = feature.at[:, pos].set(jnp.where(do_split, best_f, -1))
        split_bin = split_bin.at[:, pos].set(best_b)
        left_child = left_child.at[:, pos].set(
            jnp.where(do_split, 2 * pos + 1, -1))
        right_child = right_child.at[:, pos].set(
            jnp.where(do_split, 2 * pos + 2, -1))

        if exact:
            carried = _child_totals(hist, totals, best_f, best_b, do_split)

        node, active = _route_rows(bins, local, seg_valid, node,
                                   best_f, best_b, do_split, width)

    return feature, split_bin, left_child, right_child, node_stats


# Poisson(1) inverse CDF, support 0..12: P(k > 12) ~ 6e-11 is below f32
# uniform resolution, so searchsorted(u) IS the exact Poisson(1) quantile
# function at the precision the draw sees.
_POISSON1_CDF = np.cumsum(
    [math.exp(-1.0) / math.factorial(k) for k in range(13)]).astype(np.float32)


def _poisson1(key, shape) -> jax.Array:
    """Poisson(1) bootstrap weights via inverse-CDF lookup.

    ``jax.random.poisson``'s general-rate rejection sampler costs ~69ms per
    (8, 100k) draw on v5e — 8.6ms/tree of the forest's device critical path
    (a third of the fused chunk program itself). At rate 1 the distribution
    has 13 reachable outcomes, so one uniform draw + a 13-entry searchsorted
    replaces it, trivially within the exact-int8 histogram contract (max
    weight 13 << 127). NOTE: this changes the bootstrap PRNG stream —
    same-seed forests differ from builds before this change, and the
    resume fingerprint's ``bootstrap_sampler`` key refuses pre-change
    snapshots (see ROUND5_NOTES.md)."""
    u = jax.random.uniform(key, shape)
    # Vectorized quantile: count CDF entries below u (a 13-wide broadcast
    # compare-sum; jnp.searchsorted's default method lowers to a serial
    # scan, which benchmarked SLOWER than the rejection sampler).
    cdf = jnp.asarray(_POISSON1_CDF)
    return jnp.sum(u[..., None] > cdf, axis=-1).astype(jnp.float32)


def _edges_to_thresholds(edges: np.ndarray, feature: np.ndarray, split_bin: np.ndarray):
    """Map (feature, bin) splits to serve-time thresholds: edges[f][b]."""
    thr = np.zeros(feature.shape, np.float32)
    valid = feature >= 0
    thr[valid] = edges[feature[valid], split_bin[valid]]
    return thr


# ---------------------------------------------------------------------------
# Public trainers
# ---------------------------------------------------------------------------

def resolve_tree_chunk(cfg: TreeTrainConfig, num_classes: int = 2) -> int:
    """Default trees-per-program for the forest builder — THE one place the
    chunk rule lives (bench.py's roofline accounting imports it too).

    Fused-kernel VMEM: the accumulator block is (chunk * num_classes *
    2^depth) rows x (feature_tile * n_bins) lanes of f32; 512 rows (= 8
    trees * 2 classes * depth-5 leaves, the measured budget) is the ceiling,
    so the chunk shrinks with class count and depth. The XLA loop path uses
    4 (compile time grows with the unroll)."""
    return (max(1, 512 // (num_classes * 2 ** cfg.max_depth))
            if cfg.use_pallas else 4)


def resolve_config(config: Optional[TreeTrainConfig], mesh,
                 **defaults) -> TreeTrainConfig:
    """Trainer-entry config resolution. With a mesh, the Pallas path is
    forced OFF: pallas_call has no SPMD partitioning rule, so GSPMD would
    either fail to lower or gather the full row set onto every chip — the
    distributed histogram design is the segment-sum whose psum XLA inserts."""
    cfg = config or TreeTrainConfig(**defaults)
    if mesh is not None and cfg.use_pallas:
        cfg = TreeTrainConfig(**{**cfg.__dict__, "use_pallas": False})
    return cfg


def _drain_lists_to_host(lists, n_host: int) -> int:
    """device_get the tail (>= n_host) of each accumulator list in one
    transfer; returns the new host watermark."""
    pulled = jax.device_get([lst[n_host:] for lst in lists])
    for lst, new in zip(lists, pulled):
        lst[n_host:] = new
    return len(lists[0])


# Device bin matrices are immutable, so their (min, max) is FETCHED once per
# array — but the RANGE CHECK still runs per fit, against that fit's n_bins
# (a cached pass/fail would silently skip validation when a later fit uses a
# smaller n_bins). jax arrays are unhashable, so the cache is id-keyed with
# a weakref.finalize that evicts the id when the array is collected (before
# CPython can recycle it).
_VALIDATED_BIN_RANGE: dict = {}   # id(array) -> (lo, hi)


def _cache_bins_range(x, lo: int, hi: int) -> None:
    if id(x) in _VALIDATED_BIN_RANGE:
        return  # one finalizer per array, not one per fit
    try:
        weakref.finalize(x, _VALIDATED_BIN_RANGE.pop, id(x), None)
    except TypeError:
        return  # not weakref-able: fetch on every call instead
    _VALIDATED_BIN_RANGE[id(x)] = (lo, hi)


def _prepare_inputs(X, y, num_classes, cfg, edges, mesh):
    """Shared prep: binning, per-row class stats, activity weights.

    ``X`` may be float features (binned here, on device) OR integer bin ids
    from ``bin_rows_host`` — the pre-binned path requires ``edges`` (they
    define the serve-time thresholds and can't be recovered from bins) and
    skips ``apply_bins``, so a remote-tunnel caller uploads int8 instead of
    f32.

    With a mesh, rows are padded to a data-axis multiple and sharded; padded
    rows get weight 0 so every histogram they touch sees nothing. The
    per-level segment-sums then reduce across chips (XLA-inserted psum) —
    the distributed gradient-histogram allreduce.
    """
    from fraud_detection_tpu.parallel import mesh as mesh_lib

    if not hasattr(X, "shape"):  # plain sequences stay accepted
        X = np.asarray(X, np.float32)
    prebinned = np.issubdtype(np.dtype(X.dtype), np.integer)
    if prebinned and edges is None:
        raise ValueError(
            "integer X means pre-binned input (bin_rows_host), which requires "
            "the matching edges= — thresholds cannot be recovered from bins")
    n = X.shape[0]
    if not prebinned and (edges is None or mesh is not None):
        # Quantiles are host-side; the mesh path shards from host rows.
        X = np.asarray(X, np.float32)
    y = np.asarray(y)
    if edges is None:
        edges = quantile_bin_edges(X, cfg.n_bins)
    if mesh is not None:
        Xd = mesh_lib.shard_rows(np.asarray(X), mesh)
        yd = mesh_lib.shard_rows(np.asarray(y, np.float32), mesh)
        weights = mesh_lib.shard_rows(np.ones(n, np.float32), mesh)
    else:
        # No host round-trip when the caller already staged X on device with
        # precomputed edges (transfer can dwarf training on a remote host).
        Xd = X if prebinned else jnp.asarray(X, dtype=jnp.float32)
        yd = jnp.asarray(np.asarray(y, np.float32))
        weights = jnp.ones((n,), jnp.float32)
    if prebinned:
        bins = jnp.asarray(Xd).astype(jnp.int32)
        # Integer dtype is the pre-binned signal, so validate the claim: a
        # raw integer FEATURE matrix routed here would silently index
        # histograms with garbage (clamped out-of-range ids), not error.
        # Host inputs validate in numpy; device inputs pay ONE stacked fetch
        # (two separate int() syncs would double the tunnel RTT cost inside
        # every fit) — and only ONCE per array: the matrix is immutable on
        # device, and re-fetching inside every timed bench fit inflated the
        # 0.6s DT figure by the tunnel RTT (fifth-pass review).
        if isinstance(X, np.ndarray):
            lo, hi = int(X.min()), int(X.max())
        elif id(X) in _VALIDATED_BIN_RANGE:
            lo, hi = _VALIDATED_BIN_RANGE[id(X)]  # fetched once; checked below
        else:
            lo, hi = (int(v) for v in
                      jax.device_get(jnp.stack([bins.min(), bins.max()])))
        if lo < 0 or hi >= cfg.n_bins:
            raise ValueError(
                f"pre-binned X has ids in [{lo}, {hi}] but n_bins={cfg.n_bins}; "
                "integer X must contain bin_rows_host output, not raw features")
        if not isinstance(X, np.ndarray):
            _cache_bins_range(X, lo, hi)
    else:
        bins = apply_bins(Xd, jnp.asarray(edges))
    if mesh is None:
        # Pre-pad rows/features to the Pallas tile grid ONCE: the kernel
        # wrapper otherwise re-pads (a full-matrix HBM copy) on every one of
        # the depth x rounds histogram calls. Padded rows carry weight 0 (so
        # every histogram sees nothing); padded features produce all-rows-in-
        # bin-0 columns whose split candidates are all invalid (empty right
        # child), so first-occurrence argmax never selects them. Applied on
        # the XLA path too (not just use_pallas): the forest PRNG draw
        # shapes follow the padded row/feature counts, and the two paths
        # must consume identical streams to build identical forests.
        from fraud_detection_tpu.ops.histogram import FEATURE_TILE, ROW_TILE

        n_rows, n_feat = bins.shape
        pad_n = (-n_rows) % ROW_TILE
        pad_f = (-n_feat) % FEATURE_TILE
        if pad_n or pad_f:
            bins = jnp.pad(bins, ((0, pad_n), (0, pad_f)))
            yd = jnp.pad(yd, (0, pad_n))
            weights = jnp.pad(weights, (0, pad_n))
    stats = jax.nn.one_hot(yd.astype(jnp.int32), num_classes, dtype=jnp.float32)
    return edges, bins, yd, stats, weights, n


def fit_decision_tree(
    X, y, *, num_classes: int = 2, config: Optional[TreeTrainConfig] = None,
    edges: Optional[np.ndarray] = None, mesh=None,
) -> TreeEnsemble:
    """Gini decision tree (Spark DecisionTreeClassifier semantics, maxBins binning)."""
    cfg = resolve_config(config, mesh)
    edges, bins, _, stats, weights, _ = _prepare_inputs(X, y, num_classes, cfg, edges, mesh)
    dummy_keys = jax.random.split(jax.random.PRNGKey(0), cfg.max_depth + 1)
    out = _build_tree_jit(bins, stats, weights, dummy_keys, cfg, False)
    # ONE batched transfer: five sequential np.asarray pulls cost five
    # host<->device round-trips, which dominate the fit wall-clock when the
    # device is behind a remote tunnel (~100ms RTT each).
    feat, sbin, left, right, node_stats = jax.device_get(out)
    return _assemble(
        [feat], [sbin], [left], [right], [node_stats],
        edges, np.ones(1), "decision_tree", cfg)


def fit_random_forest(
    X, y, *, n_trees: int = 100, num_classes: int = 2, seed: int = 42,
    config: Optional[TreeTrainConfig] = None, tree_chunk: Optional[int] = None,
    feature_subset: bool = True, edges: Optional[np.ndarray] = None, mesh=None,
    checkpoint_dir: Optional[str] = None, checkpoint_every: int = 10,
) -> TreeEnsemble:
    """Random forest: Poisson(1) bootstrap + per-node feature subsets.

    Spark parity notes (RandomForestClassifier, numTrees=100, depth 5,
    featureSubsetStrategy "auto" -> sqrt): bootstrap matches Spark's Poisson
    resampling; the feature subset is Bernoulli with expected size sqrt(F)
    rather than an exact sqrt(F)-subset (vectorization-friendly deviation,
    same expectation).

    ``checkpoint_dir`` snapshots every ``checkpoint_every`` trees (and at
    completion) and resumes by skipping completed chunks
    (checkpoint/train_state.py). Per-chunk PRNG keys are
    ``fold_in(root, start)`` — a pure function of (seed, start) — so resumed
    forests are bit-identical to uninterrupted ones.

    ``tree_chunk`` defaults per path: VMEM-bounded on the fused Pallas
    builder (bigger fusions amortize the shared multihot, but the kernel's
    accumulator scales with chunk * classes * 2^depth), 4 on the XLA loop
    (compile time grows with the unroll). The chunk size shapes the
    bootstrap PRNG draw, so it is part of the resume fingerprint — resuming
    a snapshot taken under a different default requires passing that
    ``tree_chunk`` explicitly (the train CLI exposes ``--tree-chunk``).
    """
    cfg = resolve_config(config, mesh)
    if tree_chunk is None:
        tree_chunk = resolve_tree_chunk(cfg, num_classes)
    edges, bins, _, stats, base_weights, n = _prepare_inputs(
        X, y, num_classes, cfg, edges, mesh)
    n_padded = bins.shape[0]

    root = jax.random.PRNGKey(seed)
    build = _build_tree_chunk

    fingerprint = None
    if checkpoint_dir is not None:
        from fraud_detection_tpu.checkpoint import train_state as ts

        if checkpoint_every < 1:
            raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
        # bootstrap_rows: the Poisson draw runs over the PADDED row count,
        # so the padded shape is part of the PRNG stream identity — a
        # snapshot from a run with different padding must refuse to resume.
        # bootstrap_sampler: the weight PRNG stream's identity — r5 swapped
        # jax.random.poisson for the inverse-CDF sampler, so a pre-swap
        # snapshot must refuse to resume (a mixed-stream forest would not
        # be bit-identical to an uninterrupted same-seed build).
        extra = {"seed": seed, "tree_chunk": tree_chunk,
                 "feature_subset": feature_subset, "num_classes": num_classes,
                 "bootstrap_rows": n_padded,
                 "bootstrap_sampler": "poisson1-icdf",
                 **ts.mesh_extra(mesh)}
        fingerprint = ts.data_fingerprint(
            cfg.__dict__, edges, n, y=np.asarray(y), extra=extra)

    feats, sbins, lefts, rights, all_stats = [], [], [], [], []
    trees_done = 0
    if checkpoint_dir is not None:
        snap = ts.load_for(checkpoint_dir, "random_forest", fingerprint)
        if snap is not None:
            progress, arrays = snap
            trees_done = min(progress, n_trees)
            if trees_done < n_trees:
                # Snap the resume point to the original chunk grid: chunk PRNG
                # keys are fold_in(root, start) with start a multiple of
                # tree_chunk, so an off-grid tail (a completed run's final
                # partial chunk being extended) must be dropped and rebuilt
                # for the extension to stay bit-identical to a fresh run.
                trees_done = (trees_done // tree_chunk) * tree_chunk
            feats.append(arrays["feature"][:trees_done])
            sbins.append(arrays["split_bin"][:trees_done])
            lefts.append(arrays["left"][:trees_done])
            rights.append(arrays["right"][:trees_done])
            all_stats.append(arrays["node_stats"][:trees_done])

    # Chunk outputs stay ON DEVICE until a snapshot or the end — a host
    # round-trip per chunk would dominate wall-clock when the host is far
    # from the device (the per-chunk arrays are a few KB).
    n_host = len(feats)  # chunks already on host (resume load)
    acc_lists = [feats, sbins, lefts, rights, all_stats]

    def drain_to_host() -> None:
        nonlocal n_host
        n_host = _drain_lists_to_host(acc_lists, n_host)

    last_saved = trees_done
    for start in range(trees_done, n_trees, tree_chunk):
        need = min(tree_chunk, n_trees - start)
        key = jax.random.fold_in(root, start)
        wkey, mkey = jax.random.split(key)
        # Always draw/build the FULL chunk: a ragged tail would compile a
        # second program shape (which costs far more than the few discarded
        # trees); extras are sliced away. Same rule on resume, so resumed
        # forests stay bit-identical to uninterrupted ones.
        weights = _poisson1(wkey, (tree_chunk, n_padded))
        weights = weights * base_weights[None, :]  # zero out mesh padding rows
        mask_keys = jax.random.split(mkey, tree_chunk * (cfg.max_depth + 1)).reshape(
            tree_chunk, cfg.max_depth + 1, -1)
        f_, b_, l_, r_, s_ = build(bins, stats, weights, mask_keys, cfg,
                                   feature_subset, edges.shape[0])
        if need != tree_chunk:
            f_, b_, l_, r_, s_ = (f_[:need], b_[:need], l_[:need],
                                  r_[:need], s_[:need])
        feats.append(f_); sbins.append(b_)
        lefts.append(l_); rights.append(r_)
        all_stats.append(s_)
        done = start + need
        # Snapshot on the cadence (each save rewrites the full accumulated
        # state, so per-chunk saves would cost O(n_trees^2) bytes) and at
        # completion (the seed for extending the forest later).
        if checkpoint_dir is not None and (
                done - last_saved >= checkpoint_every or done == n_trees):
            drain_to_host()
            ts.save_train_state(
                checkpoint_dir, "random_forest", done, fingerprint,
                {"feature": np.concatenate(feats), "split_bin": np.concatenate(sbins),
                 "left": np.concatenate(lefts), "right": np.concatenate(rights),
                 "node_stats": np.concatenate(all_stats)})
            last_saved = done
    drain_to_host()
    cat = lambda xs: list(np.concatenate(xs, axis=0))
    return _assemble(cat(feats), cat(sbins), cat(lefts), cat(rights), cat(all_stats),
                     edges, np.ones(n_trees), "random_forest", cfg)


def fit_gradient_boosting(
    X, y, *, n_rounds: int = 100, config: Optional[TreeTrainConfig] = None,
    edges: Optional[np.ndarray] = None, base_score: Optional[float] = None,
    mesh=None, checkpoint_dir: Optional[str] = None, checkpoint_every: int = 10,
) -> TreeEnsemble:
    """XGBoost-style second-order boosting (binary logloss).

    Matches SparkXGBClassifier's configuration surface (n_estimators=100,
    max_depth=5; eta/lambda live on TreeTrainConfig — learning_rate 0.3 and
    reg_lambda 1.0 defaults as in XGBoost); each round fits a regression tree
    on (grad, hess) histograms — the distributed histogram reduction is the
    psum the engine inserts when rows are sharded, standing in for Rabit
    allreduce.

    ``checkpoint_dir`` enables mid-training snapshots every
    ``checkpoint_every`` rounds (checkpoint/train_state.py — the reference
    has no training resume, SURVEY.md §5). Resume is bit-identical: the
    margin is replayed from the saved trees in round order, so the ensemble
    equals an uninterrupted run's. A snapshot taken under a different
    config/data refuses to load.
    """
    cfg = resolve_config(config, mesh, criterion="xgb")
    if cfg.criterion != "xgb":
        cfg = TreeTrainConfig(**{**cfg.__dict__, "criterion": "xgb"})
    if base_score is None:
        # Class-prior log-odds: keeps margins calibrated for rows that match
        # few features (short/empty texts) instead of defaulting to 0.
        prior = float(np.clip(np.mean(np.asarray(y, np.float64)), 1e-6, 1 - 1e-6))
        base_score = float(np.log(prior / (1.0 - prior)))
    edges, bins, yf, _, weights, n = _prepare_inputs(X, y, 2, cfg, edges, mesh)
    n_padded = bins.shape[0]

    margin = jnp.full((n_padded,), base_score, jnp.float32)
    feats, sbins, lefts, rights, leaf_vals = [], [], [], [], []

    fingerprint = None
    if checkpoint_dir is not None:
        from fraud_detection_tpu.checkpoint import train_state as ts

        if checkpoint_every < 1:
            raise ValueError(f"checkpoint_every must be >= 1, got {checkpoint_every}")
        # gain_scan: r5 changed the float-prefix summation order (cumsum ->
        # triangular matmul) — grad/hess gains can tie-break differently, so
        # pre-change boosting snapshots must refuse to resume (a mixed-math
        # ensemble would not be bit-identical to an uninterrupted run).
        extra = {"base_score": base_score, "gain_scan": "tri-matmul",
                 **ts.mesh_extra(mesh)}
        fingerprint = ts.data_fingerprint(
            cfg.__dict__, edges, n, y=np.asarray(y), extra=extra)

    start_round = 0
    if checkpoint_dir is not None:
        snap = ts.load_for(checkpoint_dir, "gradient_boosting", fingerprint)
        if snap is not None:
            progress, arrays = snap
            # Clamp: a snapshot from a longer run must not overfill a shorter
            # one (tree count would exceed n_rounds and its tree_weights).
            progress = min(progress, n_rounds)
            for r in range(progress):
                f_ = arrays["feature"][r]; b_ = arrays["split_bin"][r]
                l_ = arrays["left"][r]; r__ = arrays["right"][r]
                v_ = arrays["leaf_values"][r]
                feats.append(f_); sbins.append(b_)
                lefts.append(l_); rights.append(r__)
                leaf_vals.append(v_[:, None])
                # Replay the margin in round order — same float additions as
                # the original incremental updates, so resume is bit-exact.
                row_leaf = _row_leaves(bins, jnp.asarray(f_), jnp.asarray(b_),
                                       jnp.asarray(l_), jnp.asarray(r__),
                                       cfg.max_depth)
                margin = _update_margin(margin, row_leaf, jnp.asarray(v_))
            start_round = progress

    def snapshot(rounds_done: int) -> None:
        ts.save_train_state(
            checkpoint_dir, "gradient_boosting", rounds_done, fingerprint,
            {"feature": np.stack(feats), "split_bin": np.stack(sbins),
             "left": np.stack(lefts), "right": np.stack(rights),
             "leaf_values": np.stack([v[:, 0] for v in leaf_vals])})

    # One fused program per round, and per-tree arrays stay ON DEVICE until a
    # snapshot or the end: a host round-trip per round would dominate
    # wall-clock (the tiny (63,) tree arrays cost more in sync latency than
    # the whole histogram pass costs in compute).
    n_host = len(feats)  # rounds already materialized on host (resume replay)
    acc_lists = [feats, sbins, lefts, rights, leaf_vals]

    def drain_to_host() -> None:
        nonlocal n_host
        n_host = _drain_lists_to_host(acc_lists, n_host)

    for r in range(start_round, n_rounds):
        f_, b_, l_, r_, values, values2, row_leaf = _boost_round(
            margin, bins, yf, weights, cfg)
        # The update runs as the SAME separate program the resume replay
        # uses: fusing it into _boost_round lets XLA contract the gather-add
        # differently (fma) and break bit-identical resume.
        margin = _update_margin(margin, row_leaf, values)
        feats.append(f_); sbins.append(b_)
        lefts.append(l_); rights.append(r_)
        leaf_vals.append(values2)
        # Snapshot on the cadence AND at completion (a finished run's snapshot
        # is the seed for extending training to more rounds later).
        if checkpoint_dir is not None and (
                (r + 1) % checkpoint_every == 0 or r + 1 == n_rounds):
            drain_to_host()
            snapshot(r + 1)

    drain_to_host()
    return _assemble(feats, sbins, lefts, rights, leaf_vals,
                     edges, np.ones(n_rounds), "xgboost", cfg, bias=base_score)


@jax.jit
def _update_margin(margin, row_node, values):
    return margin + values[row_node]


#: Row-count padding ladder for the windowed refresh trainer: every retrain
#: pads its window to the smallest rung that fits, so repeated retrains of
#: drifting window sizes reuse the SAME compiled program shapes (XLA
#: compiles stay off the learn lane's steady state, the same bucket
#: discipline the serving ladder applies to micro-batches).
REFRESH_ROW_BUCKETS: Tuple[int, ...] = (512, 1024, 2048, 4096, 8192,
                                        16384, 32768)


def refresh_row_bucket(n: int,
                       buckets: Tuple[int, ...] = REFRESH_ROW_BUCKETS) -> int:
    """Smallest configured rung >= n (the top rung caps: larger windows
    must be subsampled by the caller, never silently grown into a fresh
    compile shape per retrain)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def refresh_gradient_boosting(
    ensemble: TreeEnsemble, X, y, *, n_rounds: int = 8,
    config: Optional[TreeTrainConfig] = None,
    row_buckets: Tuple[int, ...] = REFRESH_ROW_BUCKETS,
    sample_weight: Optional[np.ndarray] = None,
) -> Tuple[TreeEnsemble, dict]:
    """Warm-started incremental boosting: keep every tree of ``ensemble``
    and fit ``n_rounds`` NEW regression trees on the recent window's
    (grad, hess) statistics, starting from the live model's margins.

    This is the learn loop's retrain primitive (learn/loop.py,
    docs/online_learning.md): the window is small (thousands of rows), the
    existing trees already explain the stationary part of the traffic, and
    the new rounds only have to explain what DRIFTED — the gradients of
    rows the live model already scores correctly are near zero, so the new
    trees spend their splits on the drifted region. Each round rides the
    same fused ``_boost_round`` program (device histogram kernels on TPU,
    segment-sum elsewhere) as offline training.

    Shapes are BUCKETED: the window pads (weight-0 rows) to the smallest
    ``row_buckets`` rung that fits, so a steady retrain cadence reuses one
    compiled program instead of compiling per window size; windows larger
    than the top rung keep their most recent rows. Returns
    ``(new_ensemble, info)`` — info carries the padded rung, per-round
    shapes, and the window metadata the registry manifest records.
    """
    if ensemble.kind != "xgboost":
        raise ValueError(
            f"refresh_gradient_boosting warm-starts xgboost ensembles; got "
            f"kind {ensemble.kind!r} (gini forests have no additive margin "
            "to resume from — retrain those offline)")
    cfg = resolve_config(config, None, criterion="xgb")
    if cfg.criterion != "xgb":
        cfg = TreeTrainConfig(**{**cfg.__dict__, "criterion": "xgb"})
    if cfg.max_depth != ensemble.max_depth:
        # Node-array layouts must agree for the concat below; a different
        # depth would also silently change the candidate's latency class.
        cfg = TreeTrainConfig(**{**cfg.__dict__,
                                 "max_depth": ensemble.max_depth})
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    if X.ndim != 2 or X.shape[0] != y.shape[0]:
        raise ValueError(f"X {X.shape} / y {y.shape} mismatch")
    if X.shape[0] < 2:
        raise ValueError("refresh needs at least 2 labeled rows")
    bucket = refresh_row_bucket(X.shape[0], tuple(row_buckets))
    if X.shape[0] > bucket:
        # Over the top rung: keep the most RECENT rows (callers pass the
        # window oldest-first) — the window semantics, made explicit.
        X, y = X[-bucket:], y[-bucket:]
        if sample_weight is not None:
            sample_weight = sample_weight[-bucket:]
    n = X.shape[0]
    weights = (np.ones(n, np.float32) if sample_weight is None
               else np.asarray(sample_weight, np.float32))

    # Window-local quantile edges from the REAL rows (pads excluded): the
    # new trees' thresholds come from the drifted window's distribution.
    edges = quantile_bin_edges(X, cfg.n_bins)

    pad = bucket - n
    if pad:
        X = np.concatenate([X, np.zeros((pad, X.shape[1]), np.float32)])
        y = np.concatenate([y, np.zeros(pad, np.float32)])
        weights = np.concatenate([weights, np.zeros(pad, np.float32)])

    # Warm start: the live ensemble's margins on the window (padded rows
    # get the margin of an all-zero row — inert under weight 0).
    from fraud_detection_tpu.models import trees as trees_mod

    margin = trees_mod.predict_margin(ensemble, jnp.asarray(X))

    bins = apply_bins(jnp.asarray(X), jnp.asarray(edges))
    # Tile-align once, like _prepare_inputs (the Pallas wrapper would
    # otherwise re-pad the matrix on every level of every round).
    from fraud_detection_tpu.ops.histogram import FEATURE_TILE, ROW_TILE

    pad_n = (-bins.shape[0]) % ROW_TILE
    pad_f = (-bins.shape[1]) % FEATURE_TILE
    if pad_n or pad_f:
        bins = jnp.pad(bins, ((0, pad_n), (0, pad_f)))
        y = np.concatenate([y, np.zeros(pad_n, np.float32)])
        weights = np.concatenate([weights, np.zeros(pad_n, np.float32)])
        margin = jnp.pad(margin, (0, pad_n))
    yd = jnp.asarray(y)
    wd = jnp.asarray(weights)

    feats, sbins, lefts, rights, leaf_vals = [], [], [], [], []
    for _ in range(n_rounds):
        f_, b_, l_, r_, values, values2, row_leaf = _boost_round(
            margin, bins, yd, wd, cfg)
        margin = _update_margin(margin, row_leaf, values)
        feats.append(f_); sbins.append(b_)
        lefts.append(l_); rights.append(r_)
        leaf_vals.append(values2)
    jax.device_get(margin)  # one sync: rounds above stayed on device
    new = _assemble(feats, sbins, lefts, rights, leaf_vals, edges,
                    np.ones(n_rounds), "xgboost", cfg, bias=ensemble.bias)

    refreshed = TreeEnsemble(
        feature=jnp.concatenate([ensemble.feature, new.feature]),
        threshold=jnp.concatenate([ensemble.threshold, new.threshold]),
        left=jnp.concatenate([ensemble.left, new.left]),
        right=jnp.concatenate([ensemble.right, new.right]),
        leaf=jnp.concatenate([ensemble.leaf, new.leaf]),
        tree_weights=jnp.concatenate([ensemble.tree_weights,
                                      new.tree_weights]),
        kind="xgboost", max_depth=ensemble.max_depth, bias=ensemble.bias)
    info = {
        "window_rows": n,
        "padded_rows": bucket,
        "rounds": n_rounds,
        "base_trees": int(ensemble.num_trees),
        "total_trees": int(refreshed.num_trees),
        "n_bins": cfg.n_bins,
        "max_depth": cfg.max_depth,
    }
    return refreshed, info


@partial(jax.jit, static_argnames=("cfg",))
def _boost_round(margin, bins, yf, weights, cfg: TreeTrainConfig):
    """One boosting round as a single program: gradients, tree build, leaf
    values, row routing. Fusing these keeps dispatches per round to two
    (this + ``_update_margin``) — per-launch overhead is material when the
    host is far from the device."""
    p = jax.nn.sigmoid(margin)
    g, h = p - yf, p * (1.0 - p)
    stats = jnp.stack([g, h, jnp.ones_like(g)], axis=1)
    # The builder's final routing state IS each row's leaf position —
    # re-traversing with _row_leaves costs a per-row gather walk per round
    # (TPU serializes row-wise gathers; ~the same pathology removed from
    # _route_rows in r5). The resume REPLAY still uses _row_leaves (only
    # the trees are on disk); weight-0 padding rows are the one divergence
    # (builder leaves them at the root) and their margins are inert.
    f_, b_, l_, r_, s_, row_leaf = _build_tree(bins, stats, weights, None, cfg)
    values = -s_[:, 0] / (s_[:, 1] + cfg.reg_lambda) * cfg.learning_rate
    # values twice: flat for the margin update, (M, 1) for the snapshot
    # accumulator — shaping in-program avoids a per-round dispatch.
    return f_, b_, l_, r_, values, values[:, None], row_leaf


@partial(jax.jit, static_argnames=("max_depth",))
def _row_leaves(bins, feature, split_bin, left, right, max_depth: int):
    """Leaf heap-position per row, in bin space (train-time traversal)."""

    def body(_, node):
        f = feature[node]
        is_leaf = left[node] < 0
        row_bin = jnp.take_along_axis(bins, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        nxt = jnp.where(row_bin <= split_bin[node], left[node], right[node])
        return jnp.where(is_leaf, node, nxt)

    n = bins.shape[0]
    return jax.lax.fori_loop(0, max_depth, body, jnp.zeros((n,), jnp.int32))


def _assemble(feats, sbins, lefts, rights, payloads, edges, tree_weights,
              kind: str, cfg: TreeTrainConfig, bias: float = 0.0) -> TreeEnsemble:
    """Stack per-tree flat arrays into a TreeEnsemble with real thresholds."""
    feature = np.stack(feats).astype(np.int32)
    split_bin = np.stack(sbins).astype(np.int32)
    thresholds = np.stack([
        _edges_to_thresholds(edges, f, b) for f, b in zip(feature, split_bin)])
    return TreeEnsemble(
        feature=jnp.asarray(feature),
        threshold=jnp.asarray(thresholds),
        left=jnp.asarray(np.stack(lefts).astype(np.int32)),
        right=jnp.asarray(np.stack(rights).astype(np.int32)),
        leaf=jnp.asarray(np.stack(payloads).astype(np.float32)),
        tree_weights=jnp.asarray(np.asarray(tree_weights, np.float32)),
        kind=kind,
        max_depth=cfg.max_depth,
        bias=bias,
    )
