"""Tree ensembles as flat arrays with vectorized TPU traversal.

TPU-first redesign of the reference's tree models (Spark MLlib DecisionTree /
RandomForest and XGBoost — fraud_detection_spark.py:56-91). Spark walks
pointer-linked node objects per row on the JVM; here every ensemble is a
struct-of-arrays pytree

    feature   int32 (T, M)   split feature per node (-1 at leaves/padding)
    threshold f32   (T, M)   continuous split threshold ("go left if <=")
    left      int32 (T, M)   left-child index (-1 at leaves)
    right     int32 (T, M)
    leaf      f32   (T, M, C) leaf payload: class stats (classifiers, C>=2)
                              or scalar score (boosting, C=1)
    tree_weights f32 (T,)

and traversal is a fixed-bound ``lax.fori_loop`` (max_depth steps, staying put
at leaves) vmapped over batch and trees — no data-dependent control flow, so
XLA compiles one dense program that batches thousands of rows per dispatch.

Prediction semantics match Spark exactly:
  * decision_tree: leaf class counts -> normalized probabilities -> argmax.
  * random_forest: per-tree normalized leaf probabilities are summed and
    divided by the number of trees (Spark RandomForestClassificationModel
    raw/probability computation), then argmax.
  * gbt: margin = sum_t weight_t * leaf_scalar_t; probability of class 1 is
    sigmoid(2 * margin) (Spark GBTClassificationModel logloss link).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fraud_detection_tpu.checkpoint.spark_artifact import TreeEnsembleStage, TreeNode


@jax.tree_util.register_dataclass
@dataclass
class TreeEnsemble:
    feature: jax.Array        # (T, M) int32
    threshold: jax.Array      # (T, M) f32
    left: jax.Array           # (T, M) int32
    right: jax.Array          # (T, M) int32
    leaf: jax.Array           # (T, M, C) f32
    tree_weights: jax.Array   # (T,) f32
    kind: str = field(metadata=dict(static=True), default="decision_tree")
    max_depth: int = field(metadata=dict(static=True), default=8)
    # Margin offset for boosted ensembles (XGBoost base_score in log-odds);
    # 0 for Spark GBT artifacts and classification forests.
    bias: float = field(metadata=dict(static=True), default=0.0)

    @property
    def num_trees(self) -> int:
        return self.feature.shape[0]

    @property
    def num_outputs(self) -> int:
        return self.leaf.shape[-1]


def from_spark_stage(stage: TreeEnsembleStage, max_depth: int | None = None) -> TreeEnsemble:
    """Decode a loaded Spark tree stage into the flat-array ensemble.

    Spark stores nodes in preorder with explicit child ids; leaf payload for
    classifiers is the impurityStats class-count vector (normalized at
    predict time), for GBT regression trees the scalar prediction.
    """
    trees = stage.trees
    m = max(len(t) for t in trees)
    num_classes = max(stage.num_classes, 2)
    is_gbt = stage.kind == "gbt"
    c = 1 if is_gbt else num_classes

    feature = np.full((len(trees), m), -1, np.int32)
    threshold = np.zeros((len(trees), m), np.float32)
    left = np.full((len(trees), m), -1, np.int32)
    right = np.full((len(trees), m), -1, np.int32)
    leaf = np.zeros((len(trees), m, c), np.float32)
    depth = 0

    for t, nodes in enumerate(trees):
        id_map = {n.id: i for i, n in enumerate(nodes)}
        for n in nodes:
            i = id_map[n.id]
            if n.left >= 0:
                feature[t, i] = n.split_feature
                threshold[t, i] = n.split_threshold
                left[t, i] = id_map[n.left]
                right[t, i] = id_map[n.right]
            if is_gbt:
                leaf[t, i, 0] = n.prediction
            elif n.impurity_stats.size:
                leaf[t, i, : n.impurity_stats.size] = n.impurity_stats
            else:  # stats absent: one-hot the predicted class
                leaf[t, i, int(n.prediction)] = 1.0
        depth = max(depth, _tree_depth(nodes, id_map))

    return TreeEnsemble(
        feature=jnp.asarray(feature),
        threshold=jnp.asarray(threshold),
        left=jnp.asarray(left),
        right=jnp.asarray(right),
        leaf=jnp.asarray(leaf),
        tree_weights=jnp.asarray(np.asarray(stage.tree_weights, np.float32)),
        kind=stage.kind,
        max_depth=max_depth if max_depth is not None else max(depth, 1),
    )


def _tree_depth(nodes: Sequence[TreeNode], id_map) -> int:
    depth = {0: 0}
    out = 0
    for n in sorted(nodes, key=lambda n: n.id):
        i = id_map[n.id]
        d = depth.get(i, 0)
        out = max(out, d)
        if n.left >= 0:
            depth[id_map[n.left]] = d + 1
            depth[id_map[n.right]] = d + 1
    return out


def _leaf_index_one_tree(x, feature, threshold, left, right, max_depth: int):
    """Index of the leaf that row ``x`` (F,) lands in for one tree."""

    def body(_, idx):
        is_leaf = left[idx] < 0
        go_left = x[feature[idx]] <= threshold[idx]
        nxt = jnp.where(go_left, left[idx], right[idx])
        return jnp.where(is_leaf, idx, nxt)

    return jax.lax.fori_loop(0, max_depth, body, jnp.int32(0))


@partial(jax.jit, static_argnames=("max_depth",))
def _leaf_indices(x, feature, threshold, left, right, max_depth: int):
    """(B, F) x (T-tree arrays) -> (B, T) leaf indices."""
    per_tree = jax.vmap(_leaf_index_one_tree, in_axes=(None, 0, 0, 0, 0, None))
    per_row = jax.vmap(per_tree, in_axes=(0, None, None, None, None, None))
    return per_row(x, feature, threshold, left, right, max_depth)


@partial(jax.jit, static_argnames=("max_depth",))
def _leaf_indices_encoded(ids, counts, idf, feature, threshold, left, right,
                          max_depth: int):
    """Hashed sparse rows (B, W) -> (B, T) leaf indices WITHOUT densifying.

    A depth-5 tree reads at most 31 distinct features per row, so
    materializing the (B, F) dense TF-IDF matrix (an XLA scatter — slow,
    serialized on TPU) just to gather a handful of values back is the wrong
    shape. Instead the value of the current node's split feature is computed
    on demand from the row's term list: sum of counts whose hashed id equals
    the feature, scaled by its IDF — identical math to the dense path
    (absent features read 0 both ways; padded term slots carry count 0)."""

    def one_row(ids_row, counts_row):
        def one_tree(feat, thr, l, r):
            def body(_, idx):
                f = jnp.maximum(feat[idx], 0)    # leaves carry -1; unused
                val = jnp.sum(
                    jnp.where(ids_row == f, counts_row, 0.0)) * idf[f]
                is_leaf = l[idx] < 0
                nxt = jnp.where(val <= thr[idx], l[idx], r[idx])
                return jnp.where(is_leaf, idx, nxt)

            return jax.lax.fori_loop(0, max_depth, body, jnp.int32(0))

        return jax.vmap(one_tree)(feature, threshold, left, right)

    return jax.vmap(one_row)(ids, counts.astype(jnp.float32))


def _proba_from_leaf_indices(ensemble: TreeEnsemble, idx: jax.Array) -> jax.Array:
    """(B, T) leaf indices -> (B, C) class probabilities (Spark semantics)."""
    payload = jnp.take_along_axis(
        ensemble.leaf[None], idx[:, :, None, None], axis=2)[:, :, 0, :]  # (B, T, C)

    if ensemble.kind in ("gbt", "xgboost"):
        margin = ensemble.bias + jnp.sum(
            payload[..., 0] * ensemble.tree_weights[None, :], axis=1)
        # Spark GBT's logloss link is sigmoid(2*margin); XGBoost's is sigmoid(margin).
        scale = 2.0 if ensemble.kind == "gbt" else 1.0
        p1 = jax.nn.sigmoid(scale * margin)
        return jnp.stack([1.0 - p1, p1], axis=-1)

    # Normalize each tree's leaf stats to probabilities, then average with
    # tree weights (all-ones for DT/RF; Spark divides by numTrees).
    per_tree = payload / jnp.maximum(payload.sum(-1, keepdims=True), 1e-12)
    weighted = per_tree * ensemble.tree_weights[None, :, None]
    raw = weighted.sum(axis=1)
    return raw / jnp.maximum(raw.sum(-1, keepdims=True), 1e-12)


def predict_proba(ensemble: TreeEnsemble, x: jax.Array) -> jax.Array:
    """(B, F) dense features -> (B, C) class probabilities (Spark semantics)."""
    idx = _leaf_indices(x, ensemble.feature, ensemble.threshold,
                        ensemble.left, ensemble.right, ensemble.max_depth)  # (B, T)
    return _proba_from_leaf_indices(ensemble, idx)


def predict_proba_encoded(ensemble: TreeEnsemble, ids, counts, idf) -> jax.Array:
    """Hashed sparse rows -> (B, C) probabilities via the scatter-free
    traversal (the serving fast path; bit-consistent with predict_proba on
    the densified rows)."""
    idx = _leaf_indices_encoded(ids, counts, idf, ensemble.feature,
                                ensemble.threshold, ensemble.left,
                                ensemble.right, ensemble.max_depth)
    return _proba_from_leaf_indices(ensemble, idx)


def predict(ensemble: TreeEnsemble, x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Returns (predicted class int32 (B,), probability of class 1 (B,))."""
    proba = predict_proba(ensemble, x)
    return jnp.argmax(proba, axis=-1).astype(jnp.int32), proba[..., 1]


def predict_margin(ensemble: TreeEnsemble, x: jax.Array) -> jax.Array:
    """(B, F) dense features -> (B,) raw boosting margin (bias + weighted
    leaf sum) for boosted ensembles — the warm-start seed the incremental
    refresh trainer resumes from (models/train_trees.py
    ``refresh_gradient_boosting``). ``sigmoid(margin)`` (xgboost kind)
    equals ``predict_proba(...)[:, 1]`` exactly; pinned in test_learn.py."""
    if ensemble.kind not in ("gbt", "xgboost"):
        raise ValueError(
            f"predict_margin applies to boosted ensembles, not "
            f"{ensemble.kind!r} (classification forests carry class "
            "stats, not additive margins)")
    idx = _leaf_indices(x, ensemble.feature, ensemble.threshold,
                        ensemble.left, ensemble.right, ensemble.max_depth)
    payload = jnp.take_along_axis(
        ensemble.leaf[None], idx[:, :, None, None], axis=2)[:, :, 0, 0]
    return ensemble.bias + jnp.sum(
        payload * ensemble.tree_weights[None, :], axis=1)


def feature_importances(ensemble_stage: TreeEnsembleStage, num_features: int) -> np.ndarray:
    """Spark-style gain-weighted feature importances (normalized to sum 1).

    Matches treeModel.featureImportances semantics: per tree, each internal
    node contributes gain * rawCount to its split feature; per-tree vectors
    are normalized then averaged over trees and re-normalized
    (reference consumes this at fraud_detection_spark.py:231-246).
    """
    total = np.zeros(num_features, np.float64)
    for nodes in ensemble_stage.trees:
        imp = np.zeros(num_features, np.float64)
        counts = {n.id: (n.impurity_stats.sum() if n.impurity_stats.size else 0.0)
                  for n in nodes}
        for n in nodes:
            if n.left >= 0 and n.split_feature >= 0 and n.gain > 0:
                imp[n.split_feature] += n.gain * max(counts.get(n.id, 0.0), 1.0)
        s = imp.sum()
        if s > 0:
            total += imp / s
    s = total.sum()
    return total / s if s > 0 else total
