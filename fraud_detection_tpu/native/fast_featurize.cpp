// Native host-side featurizer: clean -> tokenize -> stopword filter ->
// MurmurHash3_x86_32 bucket -> per-doc counts, batch-assembled into the
// padded (B, L) arrays the device program consumes.
//
// This is the one justified native component (SURVEY.md §7 hard part 3):
// at the 10k+ msgs/sec target the Python per-token loop starves the TPU; the
// math here is trivial but must be BIT-EXACT with the Python reference
// implementation in featurize/{text,hashing}.py, which itself carries Spark
// parity (Tokenizer / StopWordsRemover / ml.feature.HashingTF semantics of
// the shipped artifact — /root/reference/dialogue_classification_model).
//
// Parity contract replicated here:
//  * clean: Unicode-lowercase then keep only [a-z ]. For non-ASCII input the
//    only codepoints whose Python str.lower() yields an ASCII letter are
//    U+0130 (-> "i" + combining dot, dot stripped) and U+212A (Kelvin -> k);
//    both are special-cased, every other non-ASCII byte sequence strips.
//  * tokenize: Java String.split("\\s") semantics on the cleaned text —
//    leading/interior empty strings kept, trailing dropped, and splitting ""
//    returns [""] (the empty token is real: it flows through the stopword
//    filter and hashes into bucket murmur3("", 42) % F).
//  * stopwords: exact-match set (the Python side lowercases the list for the
//    case-insensitive default before handing it over).
//  * hash: standard MurmurHash3_x86_32 over UTF-8 bytes, seed 42, then
//    Spark's nonNegativeMod on the SIGNED hash.
//  * row assembly: unique buckets sorted ascending; if a row has more unique
//    buckets than L, keep the L highest counts (ties: lowest bucket id
//    first — numpy argsort(-val) stable-order semantics), then re-sort by id.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread fast_featurize.cpp -o libfastfeat.so

#include <algorithm>
#include <atomic>
#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_set>
#include <vector>

namespace {

constexpr uint32_t C1 = 0xcc9e2d51u;
constexpr uint32_t C2 = 0x1b873593u;

inline uint32_t rotl32(uint32_t x, int r) { return (x << r) | (x >> (32 - r)); }

inline uint32_t mix_k1(uint32_t k1) {
  k1 *= C1;
  k1 = rotl32(k1, 15);
  return k1 * C2;
}

inline uint32_t mix_h1(uint32_t h1, uint32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  return h1 * 5u + 0xe6546b64u;
}

uint32_t murmur3_x86_32(const unsigned char* data, size_t len, uint32_t seed) {
  uint32_t h1 = seed;
  const size_t aligned = len & ~size_t(3);
  for (size_t i = 0; i < aligned; i += 4) {
    uint32_t k1 = uint32_t(data[i]) | (uint32_t(data[i + 1]) << 8) |
                  (uint32_t(data[i + 2]) << 16) | (uint32_t(data[i + 3]) << 24);
    h1 = mix_h1(h1, mix_k1(k1));
  }
  uint32_t k1 = 0;
  int shift = 0;
  for (size_t i = aligned; i < len; ++i) {
    k1 ^= uint32_t(data[i]) << shift;
    shift += 8;
  }
  h1 ^= mix_k1(k1);  // note: applied even when tail is empty (matches Spark)
  h1 ^= uint32_t(len);
  h1 ^= h1 >> 16;
  h1 *= 0x85ebca6bu;
  h1 ^= h1 >> 13;
  h1 *= 0xc2b2ae35u;
  h1 ^= h1 >> 16;
  return h1;
}

inline int non_negative_mod(int32_t x, int32_t mod) {
  int32_t r = x % mod;
  return r < 0 ? r + mod : r;
}

inline int hash_bucket(std::string_view term, int num_features) {
  uint32_t h = murmur3_x86_32(
      reinterpret_cast<const unsigned char*>(term.data()), term.size(), 42u);
  return non_negative_mod(static_cast<int32_t>(h), num_features);
}

struct Featurizer {
  int num_features;
  bool binary;
  bool remove_stopwords;
  std::vector<std::string> stopword_storage;          // owns the bytes
  std::unordered_set<std::string_view> stopwords;     // views into storage
  // Murmur-keyed open-addressing stopword table: tokens are murmur3-hashed
  // exactly once, and that hash serves BOTH the stopword probe and the
  // feature bucket — the std::hash pass of an unordered_set per token was
  // ~20% of single-core encode time.
  std::vector<std::pair<uint32_t, std::string_view>> stop_table;
  uint32_t stop_mask = 0;
  bool empty_is_stop = false;
  int empty_bucket = 0;  // bucket of the "" token (Java "".split -> [""])
  // per-batch scratch (kept between begin/fill calls; capacity persists
  // across batches so steady-state encodes do zero row allocations)
  std::vector<std::vector<std::pair<int, float>>> rows;  // sorted by bucket id
  int n_rows = 0;

  void build_stop_table() {
    size_t cap = 8;
    while (cap < stopwords.size() * 2 + 1) cap <<= 1;
    stop_table.assign(cap, {0u, std::string_view()});
    stop_mask = uint32_t(cap - 1);
    for (const auto& s : stopwords) {
      uint32_t h = murmur3_x86_32(
          reinterpret_cast<const unsigned char*>(s.data()), s.size(), 42u);
      uint32_t i = h & stop_mask;
      while (stop_table[i].second.data() != nullptr) i = (i + 1) & stop_mask;
      stop_table[i] = {h, s};
    }
    empty_is_stop = stopwords.count(std::string_view()) > 0;
    empty_bucket = hash_bucket(std::string_view(), num_features);
  }

  inline bool is_stop(uint32_t h, const char* data, size_t len) const {
    uint32_t i = h & stop_mask;
    while (true) {
      const auto& e = stop_table[i];
      if (e.second.data() == nullptr) return false;
      if (e.first == h && e.second.size() == len &&
          std::memcmp(e.second.data(), data, len) == 0)
        return true;
      i = (i + 1) & stop_mask;
    }
  }
};

// Epoch-stamped bucket accumulator: O(1) per token with NO per-row clearing
// (the stamp marks which rows a slot was last touched in) and no per-row
// sort at all — touched buckets are tracked in a bitmap whose set-bit scan
// yields ids in ascending order directly (157 word loads at 10k features
// beats sorting ~100 ints by ~25%). Replaces the earlier sort+run-length
// pass, which was ~40% of single-core encode time at typical (~100-300
// token) dialogue sizes. One accumulator per worker thread (~80KB at 10k
// features — L2-resident).
//
// Contract: every begin_row() is followed by exactly one emit() (emit is
// what clears the bitmap; the encode paths uphold this unconditionally).
struct StampCounter {
  std::vector<uint32_t> stamp;
  std::vector<float> count;
  std::vector<uint64_t> bits;
  int nwords = 0;
  uint32_t epoch = 0;

  void init(int n) {
    if (int(stamp.size()) != n) {
      stamp.assign(n, 0);
      count.assign(n, 0.0f);
      nwords = (n + 63) / 64;
      bits.assign(nwords, 0);
      epoch = 0;
    }
  }

  inline void begin_row() {
    if (++epoch == 0) {  // uint32 wrap: stale stamps would alias; re-zero
      std::fill(stamp.begin(), stamp.end(), 0u);
      epoch = 1;
    }
  }

  inline void add(int b) {
    if (stamp[b] != epoch) {
      stamp[b] = epoch;
      count[b] = 1.0f;
      bits[b >> 6] |= 1ull << (b & 63);
    } else {
      count[b] += 1.0f;
    }
  }

  inline void add_n(int b, int k) {
    if (stamp[b] != epoch) {
      stamp[b] = epoch;
      count[b] = float(k);
      bits[b >> 6] |= 1ull << (b & 63);
    } else {
      count[b] += float(k);
    }
  }

  // Id-sorted unique (bucket, count) row via the bitmap scan (clears the
  // bitmap as it goes). Returns the row width.
  int emit(std::vector<std::pair<int, float>>& row, bool binary) {
    row.clear();
    for (int w = 0; w < nwords; ++w) {
      uint64_t m = bits[w];
      if (!m) continue;
      bits[w] = 0;
      do {
        int b = w * 64 + __builtin_ctzll(m);
        m &= m - 1;
        row.emplace_back(b, binary ? 1.0f : count[b]);
      } while (m);
    }
    return int(row.size());
  }
};

// Streaming tokenizer: consumes cleaned input (letter runs, spaces, and the
// occasional decoded escape/UTF-8 char) and emits hashed buckets — fused
// clean -> split -> stopword -> murmur with no intermediate cleaned string.
// A token made of one already-clean [a-z] source run is hashed straight from
// the source bytes (zero copy); tokens needing case-folding or assembled
// across stripped chars materialize into `tok` via bulk appends. Replicates
// Java String.split("\\s") semantics: interior empty tokens are real
// (deferred via `pending_empty` until a later non-empty token proves them
// interior), trailing empties drop, and a fully-empty input is the single
// token [""].
struct TokenSink {
  const Featurizer* f;
  StampCounter& acc;
  std::string tok;                         // materialized token (bulk appends)
  const unsigned char* span_a = nullptr;   // pure-span token: clean source run
  const unsigned char* span_b = nullptr;
  int pending_empty = 0;
  bool seen_any = false;  // any cleaned char at all (incl. spaces)

  TokenSink(const Featurizer* f_, StampCounter& a) : f(f_), acc(a) {}

  inline bool tok_empty() const { return span_a == nullptr && tok.empty(); }

  inline void materialize() {
    if (span_a != nullptr) {
      tok.append(reinterpret_cast<const char*>(span_a), size_t(span_b - span_a));
      span_a = nullptr;
    }
  }

  // Slow-path single char (decoded escapes / special UTF-8 codepoints);
  // only cleaned chars ([a-z ]) may arrive here, same contract as before.
  inline void put(char c) {
    seen_any = true;
    if (c == ' ') {
      boundary();
    } else {
      materialize();
      tok.push_back(c);
    }
  }

  // Bulk letter run [a, b) of ASCII letters; `upper` = any of them is A-Z.
  inline void letters(const unsigned char* a, const unsigned char* b, bool upper) {
    seen_any = true;
    if (!upper && tok_empty()) {  // common case: whole run is already clean
      span_a = a;
      span_b = b;
      return;
    }
    materialize();
    size_t off = tok.size();
    tok.resize(off + size_t(b - a));
    char* d = &tok[off];
    for (const unsigned char* q = a; q < b; ++q) {
      unsigned char c = *q;
      *d++ = char(c >= 'A' && c <= 'Z' ? c - 'A' + 'a' : c);
    }
  }

  inline void boundary() {  // a (cleaned) space
    seen_any = true;
    if (tok_empty())
      ++pending_empty;
    else
      emit();
  }

  inline void flush_empties() {
    if (pending_empty) {
      if (!f->remove_stopwords || !f->empty_is_stop)
        acc.add_n(f->empty_bucket, pending_empty);
      pending_empty = 0;
    }
  }

  inline void emit() {
    flush_empties();
    const char* d;
    size_t n;
    if (span_a != nullptr) {
      d = reinterpret_cast<const char*>(span_a);
      n = size_t(span_b - span_a);
    } else {
      d = tok.data();
      n = tok.size();
    }
    uint32_t h = murmur3_x86_32(reinterpret_cast<const unsigned char*>(d), n, 42u);
    if (!f->remove_stopwords || !f->is_stop(h, d, n))
      acc.add(non_negative_mod(static_cast<int32_t>(h), f->num_features));
    tok.clear();
    span_a = nullptr;
  }

  void finish() {
    if (!tok_empty()) emit();            // final non-empty segment
    else if (!seen_any) emit();          // "" -> [""] (hash of empty token)
    pending_empty = 0;                   // trailing empties drop
  }
};

inline bool is_ascii_letter(unsigned char c) {
  unsigned char l = c | 0x20;  // folds A-Z onto a-z; nothing else lands there
  return l >= 'a' && l <= 'z';
}

// Bulk-process a plain-ASCII segment [s, e) with tight per-run loops instead
// of the per-byte sink state machine; stops early at the first non-ASCII
// byte (or backslash, when `stop_backslash` — the JSON-escape path). Returns
// where it stopped.
inline const unsigned char* ascii_segment(const unsigned char* s,
                                          const unsigned char* e,
                                          TokenSink& sink, bool stop_backslash) {
  while (s < e) {
    unsigned char c = *s;
    if (c >= 0x80 || (stop_backslash && c == '\\')) break;
    if (is_ascii_letter(c)) {
      const unsigned char* run = s;
      bool upper = (c < 'a');
      do {
        ++s;
        if (s >= e) break;
        c = *s;
        upper |= (is_ascii_letter(c) && c < 'a');
      } while (is_ascii_letter(c));
      sink.letters(run, s, upper);
    } else if (c == ' ') {
      sink.boundary();
      ++s;
    } else {
      ++s;  // strips to nothing (digits, punctuation, control chars)
    }
  }
  return s;
}

// Fused clean+tokenize+hash over raw UTF-8 (the plain-text encode path).
void encode_text_utf8(const Featurizer* f, const char* text, StampCounter& acc,
                      std::vector<std::pair<int, float>>& row) {
  acc.begin_row();
  TokenSink sink(f, acc);
  const unsigned char* p = reinterpret_cast<const unsigned char*>(text);
  const unsigned char* end = p + std::strlen(text);
  while (p < end) {
    unsigned char c = *p;
    if (c < 0x80) {
      p = ascii_segment(p, end, sink, /*stop_backslash=*/false);
    } else {
      // decode one UTF-8 sequence (permissive; invalid bytes skipped)
      uint32_t cp = 0;
      int extra = 0;
      if ((c & 0xE0) == 0xC0) { cp = c & 0x1F; extra = 1; }
      else if ((c & 0xF0) == 0xE0) { cp = c & 0x0F; extra = 2; }
      else if ((c & 0xF8) == 0xF0) { cp = c & 0x07; extra = 3; }
      else { ++p; continue; }
      ++p;
      bool ok = true;
      for (int i = 0; i < extra; ++i) {
        if ((*p & 0xC0) != 0x80) { ok = false; break; }
        cp = (cp << 6) | (*p & 0x3F);
        ++p;
      }
      if (!ok) continue;
      if (cp == 0x0130) sink.put('i');       // İ -> i + U+0307(stripped)
      else if (cp == 0x212A) sink.put('k');  // Kelvin sign -> k
      // all other non-ASCII codepoints lowercase outside [a-z ] and strip
    }
  }
  sink.finish();
  acc.emit(row, f->binary);
}

// ---------------------------------------------------------------------------
// Raw-JSON fast path: scan a whole Kafka message's JSON bytes, pull out the
// target string field, and clean+tokenize it in the same pass — so the serving
// engine never runs Python json.loads / json.dumps per message. The scanner
// matches CPython json.loads semantics (strict UTF-8, control-char rejection,
// escape validation, last-duplicate-key-wins, NaN/Infinity literals) so that
// a message it accepts is exactly one the Python slow path would accept; any
// message it REJECTS is re-checked by the engine with json.loads, keeping
// behavior identical even on inputs this scanner is stricter about.
// ---------------------------------------------------------------------------

struct JsonScanner {
  const unsigned char* base;
  const unsigned char* p;
  const unsigned char* end;
  static constexpr int kMaxDepth = 512;  // stricter than CPython's recursion
                                         // limit; deeper inputs fall back to
                                         // the Python decode path

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }

  bool lit(const char* s, size_t n) {
    if (size_t(end - p) < n || std::memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }

  static bool hex4(const unsigned char* q, uint32_t* out) {
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) {
      unsigned char c = q[i];
      uint32_t d;
      if (c >= '0' && c <= '9') d = c - '0';
      else if (c >= 'a' && c <= 'f') d = c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') d = c - 'A' + 10;
      else return false;
      v = (v << 4) | d;
    }
    *out = v;
    return true;
  }

  // Strict UTF-8 validation (overlongs, surrogates, > U+10FFFF rejected —
  // the same inputs Python's bytes.decode("utf-8") rejects before json even
  // parses). Advances past one multi-byte sequence.
  bool skip_valid_utf8() {
    unsigned char c = *p;
    if (c < 0xC2) return false;  // stray continuation or overlong C0/C1 lead
    int need;
    unsigned char lo = 0x80, hi = 0xBF;
    if (c < 0xE0) need = 1;
    else if (c < 0xF0) {
      need = 2;
      if (c == 0xE0) lo = 0xA0;             // overlong
      else if (c == 0xED) hi = 0x9F;        // surrogates
    } else if (c < 0xF5) {
      need = 3;
      if (c == 0xF0) lo = 0x90;             // overlong
      else if (c == 0xF4) hi = 0x8F;        // > U+10FFFF
    } else {
      return false;
    }
    if (end - p <= need) return false;
    if (p[1] < lo || p[1] > hi) return false;
    for (int i = 2; i <= need; ++i)
      if ((p[i] & 0xC0) != 0x80) return false;
    p += need + 1;
    return true;
  }

  // Validate+skip a string starting at '"'. On success `*content_start` /
  // `*content_end` hold the offsets of the raw (still-escaped) contents.
  bool scan_string(int* content_start, int* content_end) {
    if (p >= end || *p != '"') return false;
    ++p;
    *content_start = int(p - base);
    while (p < end) {
      unsigned char c = *p;
      if (c == '"') {
        *content_end = int(p - base);
        ++p;
        return true;
      }
      if (c == '\\') {
        ++p;
        if (p >= end) return false;
        unsigned char e = *p;
        if (e == '"' || e == '\\' || e == '/' || e == 'b' || e == 'f' ||
            e == 'n' || e == 'r' || e == 't') {
          ++p;
        } else if (e == 'u') {
          ++p;
          uint32_t cp;
          if (end - p < 4 || !hex4(p, &cp)) return false;
          p += 4;
        } else {
          return false;
        }
      } else if (c < 0x20) {
        return false;  // raw control char (json.loads strict mode rejects)
      } else if (c < 0x80) {
        ++p;
      } else if (!skip_valid_utf8()) {
        return false;
      }
    }
    return false;  // unterminated
  }

  bool number() {
    if (p < end && *p == '-') ++p;
    if (p >= end) return false;
    if (*p == '0') {
      ++p;
    } else if (*p >= '1' && *p <= '9') {
      while (p < end && *p >= '0' && *p <= '9') ++p;
    } else {
      return false;
    }
    if (p < end && *p == '.') {
      ++p;
      if (p >= end || *p < '0' || *p > '9') return false;
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      ++p;
      if (p < end && (*p == '+' || *p == '-')) ++p;
      if (p >= end || *p < '0' || *p > '9') return false;
      while (p < end && *p >= '0' && *p <= '9') ++p;
    }
    return true;
  }

  bool object(int depth) {
    if (depth > kMaxDepth) return false;
    ++p;  // '{'
    ws();
    if (p < end && *p == '}') { ++p; return true; }
    while (true) {
      ws();
      int s, e;
      if (!scan_string(&s, &e)) return false;
      ws();
      if (p >= end || *p != ':') return false;
      ++p;
      if (!value(depth)) return false;
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; return true; }
      return false;
    }
  }

  bool array(int depth) {
    if (depth > kMaxDepth) return false;
    ++p;  // '['
    ws();
    if (p < end && *p == ']') { ++p; return true; }
    while (true) {
      if (!value(depth)) return false;
      ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == ']') { ++p; return true; }
      return false;
    }
  }

  bool value(int depth) {
    ws();
    if (p >= end) return false;
    switch (*p) {
      case '"': { int s, e; return scan_string(&s, &e); }
      case '{': return object(depth + 1);
      case '[': return array(depth + 1);
      case 't': return lit("true", 4);
      case 'f': return lit("false", 5);
      case 'n': return lit("null", 4);
      case 'N': return lit("NaN", 3);          // json.loads accepts these
      case 'I': return lit("Infinity", 8);
      case '-':
        if (end - p >= 9 && std::memcmp(p, "-Infinity", 9) == 0) { p += 9; return true; }
        return number();
      default: return number();
    }
  }
};

// Decode the (validated) raw contents of a JSON string literal straight into
// the fused tokenizer — escapes like \n, \", \\ all clean to nothing; \uXXXX
// goes through the same codepoint rule as raw UTF-8. No intermediate decoded
// or cleaned string is ever materialized.
void decode_clean_json(const unsigned char* s, const unsigned char* e, TokenSink& sink) {
  while (s < e) {
    unsigned char c = *s;
    if (c == '\\') {
      unsigned char esc = s[1];
      s += 2;
      if (esc == 'u') {
        uint32_t cp = 0;
        JsonScanner::hex4(s, &cp);
        s += 4;
        if (cp >= 0xD800 && cp <= 0xDBFF && e - s >= 6 && s[0] == '\\' && s[1] == 'u') {
          uint32_t lo2 = 0;
          if (JsonScanner::hex4(s + 2, &lo2) && lo2 >= 0xDC00 && lo2 <= 0xDFFF) {
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo2 - 0xDC00);
            s += 6;
          }
          // lone high surrogate: falls through as cp in D800-DBFF -> strips,
          // exactly like the surrogate char json.loads produces
        }
        if (cp < 0x80) {
          unsigned char a = (unsigned char)cp;
          if (a >= 'A' && a <= 'Z') a = a - 'A' + 'a';
          if ((a >= 'a' && a <= 'z') || a == ' ') sink.put(char(a));
        } else if (cp == 0x0130) sink.put('i');
        else if (cp == 0x212A) sink.put('k');
      }
      // " \\ / b f n r t : none land in [a-z ] after cleaning -> emit nothing
    } else if (c < 0x80) {
      s = ascii_segment(s, e, sink, /*stop_backslash=*/true);
    } else {
      // already validated UTF-8: decode the codepoint permissively
      uint32_t cp = 0;
      int extra = 0;
      if ((c & 0xE0) == 0xC0) { cp = c & 0x1F; extra = 1; }
      else if ((c & 0xF0) == 0xE0) { cp = c & 0x0F; extra = 2; }
      else { cp = c & 0x07; extra = 3; }
      ++s;
      for (int i = 0; i < extra && s < e; ++i, ++s) cp = (cp << 6) | (*s & 0x3F);
      if (cp == 0x0130) sink.put('i');
      else if (cp == 0x212A) sink.put('k');
      // cp < 0x80 impossible here (multi-byte lead); others strip
    }
  }
}

// Parse one message. Returns 1 and fills span_start/span_len (raw string
// literal INCLUDING quotes) + the tokenized row when the top level is a JSON
// object whose last `key` entry is a string; 0 otherwise (any malformation —
// the engine re-checks 0s with Python json.loads for exact-semantics routing).
int parse_json_message(const Featurizer* f, const unsigned char* base, int len,
                       std::string_view key, int32_t* span_start,
                       int32_t* span_len, StampCounter& acc,
                       std::vector<std::pair<int, float>>& row) {
  JsonScanner sc{base, base, base + len};
  sc.ws();
  if (sc.p >= sc.end || *sc.p != '{') return 0;
  ++sc.p;
  sc.ws();
  bool found = false, found_str = false;
  int fs = 0, fe = 0;  // raw contents offsets of the last matching value
  if (sc.p < sc.end && *sc.p == '}') {
    ++sc.p;
  } else {
    while (true) {
      sc.ws();
      int ks, ke;
      if (!sc.scan_string(&ks, &ke)) return 0;
      // Keys are matched on raw bytes; an escape-written key (e.g. "text")
      // decodes to a byte string this comparison can't see, so a duplicate of
      // the text field could win under json.loads last-duplicate-wins while we
      // match the literal spelling. Any escaped key disqualifies the message
      // to the exact-semantics (json.loads) slow path.
      if (std::memchr(base + ks, '\\', size_t(ke - ks)) != nullptr) return 0;
      bool is_key = size_t(ke - ks) == key.size() &&
                    std::memcmp(base + ks, key.data(), key.size()) == 0;
      sc.ws();
      if (sc.p >= sc.end || *sc.p != ':') return 0;
      ++sc.p;
      if (is_key) {
        sc.ws();
        if (sc.p < sc.end && *sc.p == '"') {
          int vs, ve;
          if (!sc.scan_string(&vs, &ve)) return 0;
          found = true;
          found_str = true;
          fs = vs;
          fe = ve;
        } else {
          if (!sc.value(1)) return 0;
          found = true;
          found_str = false;  // duplicate keys: LAST one wins (json.loads)
        }
      } else {
        if (!sc.value(1)) return 0;
      }
      sc.ws();
      if (sc.p < sc.end && *sc.p == ',') { ++sc.p; continue; }
      if (sc.p < sc.end && *sc.p == '}') { ++sc.p; break; }
      return 0;
    }
  }
  sc.ws();
  if (sc.p != sc.end) return 0;  // trailing garbage
  if (!found || !found_str) return 0;
  *span_start = fs - 1;        // include the opening quote
  *span_len = (fe - fs) + 2;   // ... and the closing one
  acc.begin_row();
  TokenSink sink(f, acc);
  decode_clean_json(base + fs, base + fe, sink);
  sink.finish();
  acc.emit(row, f->binary);
  return 1;
}

// Split [0, n) across worker threads; each shard returns its max row width
// and the overall max is returned. Docs are independent, so the batch
// parallelizes trivially (the caller holds the GIL-released ctypes call —
// this is where the host-side throughput headroom lives, SURVEY.md §7 hard
// part 3).
template <typename Fn>
int run_sharded(int n, Fn&& encode_range) {
  unsigned hw = std::thread::hardware_concurrency();
  int n_threads = std::min<int>(hw ? hw : 1, 8);
  // Thread spawn costs ~10s of microseconds each; only worth it for real batches.
  if (n_threads <= 1 || n < 256) return encode_range(0, n);

  std::atomic<int> width{0};
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  const int per = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    const int lo = t * per;
    const int hi = std::min(n, lo + per);
    if (lo >= hi) break;
    workers.emplace_back([&width, &encode_range, lo, hi] {
      int w = encode_range(lo, hi);
      int cur = width.load(std::memory_order_relaxed);
      while (w > cur &&
             !width.compare_exchange_weak(cur, w, std::memory_order_relaxed)) {
      }
    });
  }
  for (auto& w : workers) w.join();
  return width.load(std::memory_order_relaxed);
}

// Shared fill core: drain a row store into padded (n_rows, L) output arrays,
// truncating over-long rows by the parity-critical keep-top-L rule. Used by
// the handle-state fills below AND the stateless shard fills (which write a
// row-slice of a larger caller-owned array — same rule, same bytes).
template <typename IdT, typename CtT, typename IdCast, typename CtCast>
void fill_row_store(const std::vector<std::vector<std::pair<int, float>>>& rows,
                    int n_avail, IdT* ids, CtT* counts, int n_rows, int L,
                    IdCast id_cast, CtCast ct_cast) {
  std::memset(ids, 0, sizeof(IdT) * size_t(n_rows) * L);
  std::memset(counts, 0, sizeof(CtT) * size_t(n_rows) * L);
  const int n = std::min<int>(n_avail, n_rows);
  std::vector<std::pair<int, float>> kept;
  for (int d = 0; d < n; ++d) {
    auto* row = &rows[d];
    if (int(row->size()) > L) {
      // keep the L highest counts; ties resolved toward the lower bucket id
      // (numpy stable argsort(-val) over id-sorted input), then re-sort by id
      kept.assign(row->begin(), row->end());
      std::stable_sort(kept.begin(), kept.end(),
                       [](const auto& a, const auto& b) { return a.second > b.second; });
      kept.resize(L);
      std::sort(kept.begin(), kept.end());
      row = &kept;
    }
    IdT* idp = ids + size_t(d) * L;
    CtT* ctp = counts + size_t(d) * L;
    for (size_t j = 0; j < row->size(); ++j) {
      idp[j] = id_cast((*row)[j].first);
      ctp[j] = ct_cast((*row)[j].second);
    }
  }
}

template <typename IdT, typename CtT, typename IdCast, typename CtCast>
void fill_rows(Featurizer* f, IdT* ids, CtT* counts, int n_rows, int L,
               IdCast id_cast, CtCast ct_cast) {
  fill_row_store(f->rows, f->n_rows, ids, counts, n_rows, L, id_cast, ct_cast);
  f->n_rows = 0;  // rows keep their capacity for the next batch
}

// One caller-owned shard of a batch: row state for the stateless shard API
// below. The Featurizer handle is strictly READ-ONLY during shard calls
// (config + stop tables), so any number of shards may encode concurrently
// over one handle — this is the batch-shard entry point the Python
// thread-pool featurizer (featurize/parallel.py) drives, one GIL-releasing
// ctypes call per shard per phase.
struct ShardState {
  std::vector<std::vector<std::pair<int, float>>> rows;
};

}  // namespace

extern "C" {

void* ftok_create(const char** stopwords, int n_stop, int num_features,
                  int binary, int remove_stopwords) {
  auto* f = new Featurizer;
  f->num_features = num_features;
  f->binary = binary != 0;
  f->remove_stopwords = remove_stopwords != 0;
  f->stopword_storage.reserve(n_stop);  // no reallocation: views stay valid
  for (int i = 0; i < n_stop; ++i) {
    f->stopword_storage.emplace_back(stopwords[i]);
    f->stopwords.insert(std::string_view(f->stopword_storage.back()));
  }
  f->build_stop_table();
  return f;
}

void ftok_destroy(void* h) { delete static_cast<Featurizer*>(h); }

int ftok_hash_bucket(void* h, const char* term) {
  return hash_bucket(term, static_cast<Featurizer*>(h)->num_features);
}

// Tokenize+hash the batch into handle state; returns max unique-bucket width.
// Docs are independent, so the batch is split across worker threads (the
// caller holds the GIL-released ctypes call; this is where the host-side
// throughput headroom lives — SURVEY.md §7 hard part 3).
int ftok_encode_begin(void* h, const char** texts, int n_texts) {
  auto* f = static_cast<Featurizer*>(h);
  // rows keep their per-doc capacity across batches: steady-state encodes do
  // zero row allocations (assign() would free every vector each call).
  if (int(f->rows.size()) < n_texts) f->rows.resize(n_texts);
  f->n_rows = n_texts;

  auto encode_range = [f, texts](int lo, int hi) -> int {
    StampCounter acc;  // per-worker: no shared mutable state across shards
    acc.init(f->num_features);
    int width = 0;
    for (int d = lo; d < hi; ++d) {
      encode_text_utf8(f, texts[d], acc, f->rows[d]);
      width = std::max(width, int(f->rows[d].size()));
    }
    return width;
  };
  return run_sharded(n_texts, encode_range);
}

// Raw-JSON batch encode: per message, parse the JSON object, pull the string
// value of `key` (utf8, key_len bytes), clean+tokenize+hash it into the
// handle's row state (same state ftok_encode_fill reads). Outputs per
// message: status[i] (1 = encoded, 0 = malformed / key missing / non-string
// — those rows are all-padding) and the raw string literal's span in
// msgs[i] (INCLUDING both quotes) for zero-copy splicing into output JSON.
// Returns the max unique-bucket width over successfully encoded rows.
int ftok_encode_json_begin(void* h, const char** msgs, const int32_t* lens,
                           int n_msgs, const char* key, int key_len,
                           int32_t* status, int32_t* span_start,
                           int32_t* span_len) {
  auto* f = static_cast<Featurizer*>(h);
  if (int(f->rows.size()) < n_msgs) f->rows.resize(n_msgs);
  f->n_rows = n_msgs;
  std::string_view key_view(key, key_len);

  auto encode_range = [&](int lo, int hi) -> int {
    StampCounter acc;  // per-worker: no shared mutable state across shards
    acc.init(f->num_features);
    int width = 0;
    for (int d = lo; d < hi; ++d) {
      span_start[d] = 0;
      span_len[d] = 0;
      f->rows[d].clear();
      status[d] = parse_json_message(
          f, reinterpret_cast<const unsigned char*>(msgs[d]), lens[d], key_view,
          span_start + d, span_len + d, acc, f->rows[d]);
      if (status[d]) width = std::max(width, int(f->rows[d].size()));
    }
    return width;
  };
  return run_sharded(n_msgs, encode_range);
}

// Fill padded (rows, L) arrays from handle state. The truncate-to-L rule is
// parity-critical (keep the L highest counts; ties toward the lower bucket
// id — numpy stable argsort(-val) over id-sorted input — then re-sort by id)
// and shared by both output-dtype variants below.
void ftok_encode_fill(void* h, int32_t* ids, float* counts, int n_rows, int L) {
  fill_rows(static_cast<Featurizer*>(h), ids, counts, n_rows, L,
            [](int b) { return int32_t(b); },
            [](float v) { return v; });
}

// Same fill but emitting the device wire dtypes directly — int16 ids
// (callers gate on num_features <= 32767) and uint16 counts (clipped) —
// skipping the Python-side astype+copy of two (B, L) arrays.
void ftok_encode_fill16(void* h, int16_t* ids, uint16_t* counts, int n_rows, int L) {
  fill_rows(static_cast<Featurizer*>(h), ids, counts, n_rows, L,
            [](int b) { return int16_t(b); },
            [](float v) { return uint16_t(v > 65535.0f ? 65535u : uint32_t(v)); });
}

// ---------------------------------------------------------------------------
// Stateless batch-shard API. ftok_encode_begin/fill keep their row state on
// the handle (one in-flight batch per handle, caller-locked); these instead
// return an opaque shard object, so N Python worker threads can encode N
// shards of one batch CONCURRENTLY over a single handle:
//   phase 1: shard = ftok_shard_begin(h, texts, n)   (parallel; returns width)
//   barrier: L = pad(max shard widths)
//   phase 2: ftok_shard_fill16(shard, ids+lo*L, counts+lo*L, n, L) (parallel —
//            each shard writes its own row-slice of the caller's arrays)
//   ftok_shard_destroy(shard)
// Each phase is one GIL-releasing ctypes call, which is what makes the
// Python-side thread pool an actual parallelism win.
// ---------------------------------------------------------------------------

void* ftok_shard_begin(void* h, const char** texts, int n_texts,
                       int32_t* width_out) {
  auto* f = static_cast<Featurizer*>(h);
  auto* s = new ShardState;
  s->rows.resize(size_t(std::max(n_texts, 0)));
  StampCounter acc;  // per-shard: no shared mutable state with other shards
  acc.init(f->num_features);
  int width = 0;
  for (int d = 0; d < n_texts; ++d) {
    encode_text_utf8(f, texts[d], acc, s->rows[d]);
    width = std::max(width, int(s->rows[d].size()));
  }
  *width_out = width;
  return s;
}

void ftok_shard_fill(void* sh, int32_t* ids, float* counts, int n_rows, int L) {
  auto* s = static_cast<ShardState*>(sh);
  fill_row_store(s->rows, int(s->rows.size()), ids, counts, n_rows, L,
                 [](int b) { return int32_t(b); },
                 [](float v) { return v; });
}

void ftok_shard_fill16(void* sh, int16_t* ids, uint16_t* counts, int n_rows,
                       int L) {
  auto* s = static_cast<ShardState*>(sh);
  fill_row_store(s->rows, int(s->rows.size()), ids, counts, n_rows, L,
                 [](int b) { return int16_t(b); },
                 [](float v) { return uint16_t(v > 65535.0f ? 65535u : uint32_t(v)); });
}

void ftok_shard_destroy(void* sh) { delete static_cast<ShardState*>(sh); }

// Raw-JSON shard twin of ftok_shard_begin: parse+extract+tokenize one shard
// of a message batch into an opaque shard object, writing that shard's
// status/span entries into the CALLER's (disjoint) array slices. The handle
// is read-only here, so N Python worker threads fan a batch out over one
// handle exactly like the text shards — and because the caller marshals ONE
// char*[] for the whole batch and passes sub-pointers, the full array stays
// valid as the splice context for ftok_build_frames afterwards
// (featurize/parallel.py encode_json_sharded_native).
void* ftok_shard_json_begin(void* h, const char** msgs, const int32_t* lens,
                            int n_msgs, const char* key, int key_len,
                            int32_t* status, int32_t* span_start,
                            int32_t* span_len, int32_t* width_out) {
  auto* f = static_cast<Featurizer*>(h);
  auto* s = new ShardState;
  s->rows.resize(size_t(std::max(n_msgs, 0)));
  std::string_view key_view(key, size_t(key_len));
  StampCounter acc;  // per-shard: no shared mutable state with other shards
  acc.init(f->num_features);
  int width = 0;
  for (int d = 0; d < n_msgs; ++d) {
    span_start[d] = 0;
    span_len[d] = 0;
    s->rows[d].clear();
    status[d] = parse_json_message(
        f, reinterpret_cast<const unsigned char*>(msgs[d]), lens[d], key_view,
        span_start + d, span_len + d, acc, s->rows[d]);
    if (status[d]) width = std::max(width, int(s->rows[d].size()));
  }
  *width_out = width;
  return s;
}

// %.6f, locale-independent and hard-bounded: a co-loaded library calling
// setlocale must not turn the decimal point into a comma, and out-of-[0,1]
// inputs whose fixed rendering exceeds the caller's size estimate must fail
// cleanly (nullptr) instead of overrunning. Float to_chars needs libstdc++
// 11+; older C++17 toolchains take the bounded snprintf + comma-patch path
// so the on-demand build never regresses to import failure.
static inline char* format_fixed6(char* p, char* lim, double v) {
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  auto cr = std::to_chars(p, lim, v, std::chars_format::fixed, 6);
  if (cr.ec != std::errc()) return nullptr;
  return cr.ptr;
#else
  long long rem = lim - p;
  if (rem <= 1) return nullptr;
  int n = std::snprintf(p, size_t(rem), "%.6f", v);
  if (n < 0 || n >= rem) return nullptr;  // truncated: caller returns -1
  for (char* q = p; q < p + n; ++q)
    if (*q == ',') *q = '.';  // LC_NUMERIC-proof
  return p + n;
#endif
}

// Assemble the engine's classified-output wire frames for a whole batch in
// one pass (stateless — no handle). Frame layout must stay byte-identical to
// the engine's Python template path (stream/engine.py _OUT_TEMPLATE):
//   {"prediction": %d, "label": %s, "confidence": %.6f, "original_text": %s}
// The text is each message's own raw string literal INCLUDING quotes —
// spliced straight out of the message buffer (msgs[i] + span_start[i],
// span_len[i] bytes; the spans ftok_encode_json_begin reported), never
// re-encoded. The caller passes the SAME msgs array it encoded with, so no
// per-message marshalling happens on this call. labels[i] indexes
// label_jsons; rows with labels[i] < 0 or >= n_labels emit an EMPTY frame
// (ends[i] == ends[i-1]) and the caller routes them through its Python
// fallback. Returns total bytes written, or -1 if `cap` is too small.
long long ftok_build_frames(const char** msgs, const int32_t* span_start,
                            const int32_t* span_len, const int32_t* labels,
                            const double* confs, const char** label_jsons,
                            const int32_t* label_json_lens, int n_labels,
                            int n, char* out, long long cap, int64_t* ends) {
  static const char kPred[] = "{\"prediction\": ";
  static const char kLabel[] = ", \"label\": ";
  static const char kConf[] = ", \"confidence\": ";
  static const char kText[] = ", \"original_text\": ";
  char* p = out;
  char* lim = out + cap;
  for (int i = 0; i < n; ++i) {
    int lab = labels[i];
    if (lab < 0 || lab >= n_labels) {  // caller's Python path owns this row
      ends[i] = p - out;
      continue;
    }
    // worst case: prefixes+braces ~70B, label json, %.6f of a double in
    // [0, 1e6) <= 14B, int label <= 11B, text literal
    long long need = 96 + label_json_lens[lab] + span_len[i];
    if (p + need > lim) return -1;
    std::memcpy(p, kPred, sizeof(kPred) - 1); p += sizeof(kPred) - 1;
    p = std::to_chars(p, lim, lab).ptr;
    std::memcpy(p, kLabel, sizeof(kLabel) - 1); p += sizeof(kLabel) - 1;
    std::memcpy(p, label_jsons[lab], size_t(label_json_lens[lab]));
    p += label_json_lens[lab];
    std::memcpy(p, kConf, sizeof(kConf) - 1); p += sizeof(kConf) - 1;
    p = format_fixed6(p, lim, confs[i]);
    if (p == nullptr) return -1;
    // Re-check: an out-of-range confidence can out-grow the 14-byte
    // allowance inside `need` (to_chars above only bounded itself).
    if (p + (long long)(sizeof(kText) - 1) + span_len[i] + 1 > lim) return -1;
    std::memcpy(p, kText, sizeof(kText) - 1); p += sizeof(kText) - 1;
    std::memcpy(p, msgs[i] + span_start[i], size_t(span_len[i]));
    p += span_len[i];
    *p++ = '}';
    ends[i] = p - out;
  }
  return p - out;
}

}  // extern "C"
