// Native host-side featurizer: clean -> tokenize -> stopword filter ->
// MurmurHash3_x86_32 bucket -> per-doc counts, batch-assembled into the
// padded (B, L) arrays the device program consumes.
//
// This is the one justified native component (SURVEY.md §7 hard part 3):
// at the 10k+ msgs/sec target the Python per-token loop starves the TPU; the
// math here is trivial but must be BIT-EXACT with the Python reference
// implementation in featurize/{text,hashing}.py, which itself carries Spark
// parity (Tokenizer / StopWordsRemover / ml.feature.HashingTF semantics of
// the shipped artifact — /root/reference/dialogue_classification_model).
//
// Parity contract replicated here:
//  * clean: Unicode-lowercase then keep only [a-z ]. For non-ASCII input the
//    only codepoints whose Python str.lower() yields an ASCII letter are
//    U+0130 (-> "i" + combining dot, dot stripped) and U+212A (Kelvin -> k);
//    both are special-cased, every other non-ASCII byte sequence strips.
//  * tokenize: Java String.split("\\s") semantics on the cleaned text —
//    leading/interior empty strings kept, trailing dropped, and splitting ""
//    returns [""] (the empty token is real: it flows through the stopword
//    filter and hashes into bucket murmur3("", 42) % F).
//  * stopwords: exact-match set (the Python side lowercases the list for the
//    case-insensitive default before handing it over).
//  * hash: standard MurmurHash3_x86_32 over UTF-8 bytes, seed 42, then
//    Spark's nonNegativeMod on the SIGNED hash.
//  * row assembly: unique buckets sorted ascending; if a row has more unique
//    buckets than L, keep the L highest counts (ties: lowest bucket id
//    first — numpy argsort(-val) stable-order semantics), then re-sort by id.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread fast_featurize.cpp -o libfastfeat.so

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_set>
#include <vector>

namespace {

constexpr uint32_t C1 = 0xcc9e2d51u;
constexpr uint32_t C2 = 0x1b873593u;

inline uint32_t rotl32(uint32_t x, int r) { return (x << r) | (x >> (32 - r)); }

inline uint32_t mix_k1(uint32_t k1) {
  k1 *= C1;
  k1 = rotl32(k1, 15);
  return k1 * C2;
}

inline uint32_t mix_h1(uint32_t h1, uint32_t k1) {
  h1 ^= k1;
  h1 = rotl32(h1, 13);
  return h1 * 5u + 0xe6546b64u;
}

uint32_t murmur3_x86_32(const unsigned char* data, size_t len, uint32_t seed) {
  uint32_t h1 = seed;
  const size_t aligned = len & ~size_t(3);
  for (size_t i = 0; i < aligned; i += 4) {
    uint32_t k1 = uint32_t(data[i]) | (uint32_t(data[i + 1]) << 8) |
                  (uint32_t(data[i + 2]) << 16) | (uint32_t(data[i + 3]) << 24);
    h1 = mix_h1(h1, mix_k1(k1));
  }
  uint32_t k1 = 0;
  int shift = 0;
  for (size_t i = aligned; i < len; ++i) {
    k1 ^= uint32_t(data[i]) << shift;
    shift += 8;
  }
  h1 ^= mix_k1(k1);  // note: applied even when tail is empty (matches Spark)
  h1 ^= uint32_t(len);
  h1 ^= h1 >> 16;
  h1 *= 0x85ebca6bu;
  h1 ^= h1 >> 13;
  h1 *= 0xc2b2ae35u;
  h1 ^= h1 >> 16;
  return h1;
}

inline int non_negative_mod(int32_t x, int32_t mod) {
  int32_t r = x % mod;
  return r < 0 ? r + mod : r;
}

inline int hash_bucket(std::string_view term, int num_features) {
  uint32_t h = murmur3_x86_32(
      reinterpret_cast<const unsigned char*>(term.data()), term.size(), 42u);
  return non_negative_mod(static_cast<int32_t>(h), num_features);
}

// Unicode-aware clean: lowercase, keep [a-z ] only. Non-ASCII handled per the
// contract above (U+0130 -> 'i', U+212A -> 'k', everything else stripped).
void clean_utf8(const char* text, std::string& out) {
  out.clear();
  const unsigned char* p = reinterpret_cast<const unsigned char*>(text);
  while (*p) {
    unsigned char c = *p;
    if (c < 0x80) {
      if (c >= 'A' && c <= 'Z') c = c - 'A' + 'a';
      if ((c >= 'a' && c <= 'z') || c == ' ') out.push_back(char(c));
      ++p;
    } else {
      // decode one UTF-8 sequence (permissive; invalid bytes skipped)
      uint32_t cp = 0;
      int extra = 0;
      if ((c & 0xE0) == 0xC0) { cp = c & 0x1F; extra = 1; }
      else if ((c & 0xF0) == 0xE0) { cp = c & 0x0F; extra = 2; }
      else if ((c & 0xF8) == 0xF0) { cp = c & 0x07; extra = 3; }
      else { ++p; continue; }
      ++p;
      bool ok = true;
      for (int i = 0; i < extra; ++i) {
        if ((*p & 0xC0) != 0x80) { ok = false; break; }
        cp = (cp << 6) | (*p & 0x3F);
        ++p;
      }
      if (!ok) continue;
      if (cp == 0x0130) out.push_back('i');       // İ -> i + U+0307(stripped)
      else if (cp == 0x212A) out.push_back('k');  // Kelvin sign -> k
      // all other non-ASCII codepoints lowercase outside [a-z ] and strip
    }
  }
}

// Java String.split("\\s") on cleaned text (only ' ' can remain). Tokens are
// views into the cleaned buffer — zero per-token allocation.
void java_split(const std::string& s, std::vector<std::string_view>& out) {
  out.clear();
  if (s.empty()) {
    out.emplace_back();  // Java: "".split -> [""]
    return;
  }
  size_t start = 0;
  for (size_t i = 0; i <= s.size(); ++i) {
    if (i == s.size() || s[i] == ' ') {
      out.emplace_back(s.data() + start, i - start);
      start = i + 1;
    }
  }
  while (!out.empty() && out.back().empty()) out.pop_back();  // drop trailing
}

struct Featurizer {
  int num_features;
  bool binary;
  bool remove_stopwords;
  std::vector<std::string> stopword_storage;          // owns the bytes
  std::unordered_set<std::string_view> stopwords;     // views into storage
  // per-batch scratch (kept between begin/fill calls)
  std::vector<std::vector<std::pair<int, float>>> rows;  // sorted by bucket id
};

}  // namespace

extern "C" {

void* ftok_create(const char** stopwords, int n_stop, int num_features,
                  int binary, int remove_stopwords) {
  auto* f = new Featurizer;
  f->num_features = num_features;
  f->binary = binary != 0;
  f->remove_stopwords = remove_stopwords != 0;
  f->stopword_storage.reserve(n_stop);  // no reallocation: views stay valid
  for (int i = 0; i < n_stop; ++i) {
    f->stopword_storage.emplace_back(stopwords[i]);
    f->stopwords.insert(std::string_view(f->stopword_storage.back()));
  }
  return f;
}

void ftok_destroy(void* h) { delete static_cast<Featurizer*>(h); }

int ftok_hash_bucket(void* h, const char* term) {
  return hash_bucket(term, static_cast<Featurizer*>(h)->num_features);
}

// Tokenize+hash the batch into handle state; returns max unique-bucket width.
// Docs are independent, so the batch is split across worker threads (the
// caller holds the GIL-released ctypes call; this is where the host-side
// throughput headroom lives — SURVEY.md §7 hard part 3).
int ftok_encode_begin(void* h, const char** texts, int n_texts) {
  auto* f = static_cast<Featurizer*>(h);
  f->rows.assign(n_texts, {});

  auto encode_range = [f, texts](int lo, int hi) -> int {
    std::string cleaned;
    std::vector<std::string_view> toks;
    std::vector<int> buckets;
    int width = 0;
    for (int d = lo; d < hi; ++d) {
      clean_utf8(texts[d], cleaned);
      java_split(cleaned, toks);
      buckets.clear();
      for (const auto& t : toks) {
        if (f->remove_stopwords && f->stopwords.count(t)) continue;
        buckets.push_back(hash_bucket(t, f->num_features));
      }
      // sort + run-length count: yields the id-sorted unique rows directly,
      // cheaper than a hash map at typical (~100-300 token) dialogue sizes
      std::sort(buckets.begin(), buckets.end());
      auto& row = f->rows[d];
      row.clear();
      for (size_t i = 0; i < buckets.size();) {
        size_t j = i + 1;
        while (j < buckets.size() && buckets[j] == buckets[i]) ++j;
        row.emplace_back(buckets[i], f->binary ? 1.0f : float(j - i));
        i = j;
      }
      width = std::max(width, int(row.size()));
    }
    return width;
  };

  unsigned hw = std::thread::hardware_concurrency();
  int n_threads = std::min<int>(hw ? hw : 1, 8);
  // Thread spawn costs ~10s of microseconds each; only worth it for real batches.
  if (n_threads <= 1 || n_texts < 256) return encode_range(0, n_texts);

  std::atomic<int> width{0};
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  const int per = (n_texts + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    const int lo = t * per;
    const int hi = std::min(n_texts, lo + per);
    if (lo >= hi) break;
    workers.emplace_back([&width, &encode_range, lo, hi] {
      int w = encode_range(lo, hi);
      int cur = width.load(std::memory_order_relaxed);
      while (w > cur &&
             !width.compare_exchange_weak(cur, w, std::memory_order_relaxed)) {
      }
    });
  }
  for (auto& w : workers) w.join();
  return width.load(std::memory_order_relaxed);
}

// Fill padded (rows, L) arrays from handle state; frees the state.
void ftok_encode_fill(void* h, int32_t* ids, float* counts, int n_rows, int L) {
  auto* f = static_cast<Featurizer*>(h);
  std::memset(ids, 0, sizeof(int32_t) * size_t(n_rows) * L);
  std::memset(counts, 0, sizeof(float) * size_t(n_rows) * L);
  const int n = std::min<int>(f->rows.size(), n_rows);
  std::vector<std::pair<int, float>> kept;
  for (int d = 0; d < n; ++d) {
    auto* row = &f->rows[d];
    if (int(row->size()) > L) {
      // keep the L highest counts; ties resolved toward the lower bucket id
      // (numpy stable argsort(-val) over id-sorted input), then re-sort by id
      kept.assign(row->begin(), row->end());
      std::stable_sort(kept.begin(), kept.end(),
                       [](const auto& a, const auto& b) { return a.second > b.second; });
      kept.resize(L);
      std::sort(kept.begin(), kept.end());
      row = &kept;
    }
    int32_t* idp = ids + size_t(d) * L;
    float* ctp = counts + size_t(d) * L;
    for (size_t j = 0; j < row->size(); ++j) {
      idp[j] = (*row)[j].first;
      ctp[j] = (*row)[j].second;
    }
  }
  f->rows.clear();
}

}  // extern "C"
