#!/usr/bin/env python
"""Sanitizer drive of the native featurizer ABI (ASan+UBSan / TSan).

The multi-thread ``ftok_shard_*`` entry points run N pool threads over ONE
shared C++ handle — exactly the shape a race detector exists for, and
(SURVEY.md §5) the one thing no test had ever run under a real sanitizer.
This script is the workload the CI ``sanitizers`` job (and
tests/test_sanitizers.py) runs inside an instrumented process:

  1. byte parity: serial ``encode()`` vs thread-pool sharded assembly, both
     int32/float32 and the int16/uint16 wire dtypes, over a corpus with
     unicode, embedded NULs, empty strings and stopwords;
  2. a shard hammer: several driver threads concurrently shard-encoding
     over the SAME handle (the documented read-only-handle contract);
  3. the raw-JSON scanner + native frame assembler (``encode_json`` /
     ``build_frames``) for ASan/UBSan coverage of the parsing/formatting
     paths, with frame-level JSON round-trip checks.

Run standalone — the script loads ``featurize/native.py`` and
``featurize/parallel.py`` BY FILE PATH under a stub package, so nothing
imports JAX: the sanitized process stays small, fast and low-noise.

    LD_PRELOAD=$(gcc -print-file-name=libasan.so) \
    ASAN_OPTIONS=detect_leaks=0 \
    python fraud_detection_tpu/native/san_driver.py --variant asan

Exit 0 = every check passed and the sanitizer stayed silent (sanitizer
findings abort the process via halt_on_error / -fno-sanitize-recover).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import random
import sys
import threading
import types

_HERE = os.path.dirname(os.path.abspath(__file__))
_PKG_DIR = os.path.dirname(_HERE)


def _load_by_path(modname: str, relpath: str):
    """Import a package module from its file WITHOUT running the package
    __init__ (which would pull JAX into the sanitized process)."""
    if "fraud_detection_tpu" not in sys.modules:
        stub = types.ModuleType("fraud_detection_tpu")
        stub.__path__ = [_PKG_DIR]
        sys.modules["fraud_detection_tpu"] = stub
    spec = importlib.util.spec_from_file_location(
        modname, os.path.join(_PKG_DIR, relpath))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


_STOPWORDS = ["the", "a", "an", "is", "to", "and", "of", "in", "you", "your"]


def _corpus(n: int, seed: int) -> list:
    rng = random.Random(seed)
    words = ["urgent", "account", "suspended", "verify", "social",
             "security", "winner", "congratulations", "appointment",
             "insurance", "transfer", "immediately", "the", "you", "claim",
             "café", "naïve", "詐欺", "\U0001f4b8"]
    texts = []
    for i in range(n):
        k = rng.randrange(0, 60)
        t = " ".join(rng.choice(words) for _ in range(k))
        if i % 17 == 0:
            t += " embedded\x00nul"
        if i % 23 == 0:
            t = ""
        if i % 29 == 0:
            t = "x" * 4000   # one long row per few shards
        texts.append(t)
    return texts


def _pad16(w: int) -> int:
    return max(16, (w + 15) // 16 * 16)


def check(label: str, ok: bool, detail: str = "") -> None:
    if not ok:
        print(f"FAIL {label}: {detail}", file=sys.stderr)
        sys.exit(1)
    print(f"ok   {label}")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--variant", default=os.environ.get(
        "FRAUD_TPU_NATIVE_VARIANT", "plain"))
    parser.add_argument("--threads", type=int, default=8)
    parser.add_argument("--rounds", type=int, default=6)
    parser.add_argument("--rows", type=int, default=512)
    args = parser.parse_args()
    if args.variant != "plain":
        os.environ["FRAUD_TPU_NATIVE_VARIANT"] = args.variant

    native = _load_by_path("fraud_detection_tpu.featurize.native",
                           os.path.join("featurize", "native.py"))
    parallel = _load_by_path("fraud_detection_tpu.featurize.parallel",
                             os.path.join("featurize", "parallel.py"))
    import numpy as np

    lib = native.load_library()
    check("library loads", lib is not None,
          f"variant={args.variant!r}: build failed or toolchain missing")
    feat = native.NativeFeaturizer(_STOPWORDS, num_features=4096,
                                   binary=False, remove_stopwords=True)
    check("shard ABI present", feat.supports_shards(),
          "library predates ftok_shard_*")

    texts = _corpus(args.rows, seed=1234)
    rows = args.rows + 32          # trailing all-padding rows, like serving

    # --- 1. serial vs sharded byte parity (both wire dtypes) -------------
    for want16 in (False, True):
        ids_s, cnt_s = feat.encode(texts, rows, None, _pad16, want16=want16)
        for workers in (2, 3, args.threads):
            ids_p, cnt_p = parallel.encode_sharded_native(
                feat, texts, rows, None, _pad16, want16, workers)
            check(f"parity want16={want16} workers={workers}",
                  (ids_s.dtype == ids_p.dtype
                   and np.array_equal(ids_s, ids_p)
                   and np.array_equal(cnt_s, cnt_p)),
                  "sharded encode diverged from serial bytes")

    # --- 2. concurrent shard hammer over ONE handle ----------------------
    errors: list = []

    def hammer(tid: int) -> None:
        try:
            rng = random.Random(tid)
            for r in range(args.rounds):
                sub = _corpus(128 + 16 * (tid % 3), seed=tid * 997 + r)
                ids_a, cnt_a = parallel.encode_sharded_native(
                    feat, sub, len(sub), None, _pad16,
                    bool(r % 2), 2 + (tid + r) % 3)
                if int(ids_a.shape[0]) != len(sub):
                    raise AssertionError("row count mismatch")
                # raw ABI: begin/fill/destroy directly, same handle
                buf = [feat.sanitize(t) for t in sub[: 64]]
                shard, width = feat.shard_begin(buf)
                try:
                    length = _pad16(max(width, 1))
                    ids = np.zeros((64, length), np.int32)
                    cnt = np.zeros((64, length), np.float32)
                    feat.shard_fill_into(shard, ids, cnt, 64, length)
                finally:
                    feat.shard_destroy(shard)
        except BaseException as e:  # noqa: BLE001 — relayed to the exit code
            errors.append((tid, repr(e)))

    threads = [threading.Thread(target=hammer, args=(i,), daemon=True)
               for i in range(args.threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    check(f"shard hammer x{args.threads}", not errors, repr(errors[:3]))

    # --- 3. raw-JSON scanner + native frame assembly ---------------------
    if feat.supports_json():
        values = []
        for i, t in enumerate(texts[:256]):
            if i % 13 == 0:
                values.append(b'{"broken json')           # malformed
            elif i % 11 == 0:
                values.append(json.dumps({"other": t}).encode())  # no field
            else:
                values.append(json.dumps({"text": t}).encode())
        ids, cnt, status, s_start, s_len, arr = feat.encode_json(
            values, b"text", len(values), None, _pad16)
        ok = all((status[i] == 0) or
                 (values[i][s_start[i]] == ord('"')
                  and values[i][s_start[i] + s_len[i] - 1] == ord('"'))
                 for i in range(len(values)))
        check("encode_json spans", ok, "span does not cover quoted literal")
        if native.frames_available():
            n = len(values)
            labels = np.where(status == 0, -1,
                              np.arange(n) % 2).astype(np.int32)
            confs = np.linspace(0.0, 1.0, n).astype(np.float64)
            blob, ends = native.build_frames(
                arr, s_start, s_len, labels, confs,
                [b'"benign"', b'"fraud"'])
            start = 0
            for i, end in enumerate(ends.tolist()):
                frame = blob[start:end]
                if labels[i] < 0:
                    if frame:
                        check("malformed frame empty", False, repr(frame))
                else:
                    rec = json.loads(frame)
                    if rec["prediction"] != int(labels[i]):
                        check("frame label", False, repr(rec))
                    start = end
            check("build_frames round-trip", True)
    print(f"san_driver: all checks passed (variant={args.variant}, "
          f"threads={args.threads}, rounds={args.rounds})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
