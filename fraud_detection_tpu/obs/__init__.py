"""Observability: row/batch tracing, unified metrics, telemetry export.

The serving tree's attribution layer (docs/observability.md): correlation
ids minted at poll ride every row to its terminal, per-stage wall time
feeds mergeable quantile sketches, and one metrics registry maps every
``health()`` block into Prometheus text + JSON served by file, HTTP, and
the fleet bus.
"""

from fraud_detection_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                             MetricsRegistry, leaf_paths,
                                             metric_name, parse_prometheus)
from fraud_detection_tpu.obs.trace import (BatchTrace, RowTracer, Span,
                                           SpanRing, aggregate_stage_wires,
                                           fleet_stage_latency)

__all__ = [
    "BatchTrace", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "RowTracer", "Span", "SpanRing", "aggregate_stage_wires",
    "fleet_stage_latency", "leaf_paths", "metric_name", "parse_prometheus",
]
