"""Observability: row/batch tracing, unified metrics, telemetry export.

The serving tree's attribution layer (docs/observability.md): correlation
ids minted at poll ride every row to its terminal, per-stage wall time
feeds mergeable quantile sketches, and one metrics registry maps every
``health()`` block into Prometheus text + JSON served by file, HTTP, and
the fleet bus. The sentinel (obs/sentinel/) closes the loop: declarative
alert rules over periodic metric snapshots drive a pending→firing→resolved
incident lifecycle, every transition captures a flight-recorder bundle,
and ``/healthz`` readiness flips on critical alerts.
"""

from fraud_detection_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                             MetricsRegistry, leaf_paths,
                                             metric_name, parse_prometheus)
from fraud_detection_tpu.obs.sentinel import (AlertRule, IncidentRecorder,
                                              Sentinel, default_rule_pack,
                                              fleet_rule_pack, load_rules,
                                              start_sentinel)
from fraud_detection_tpu.obs.trace import (BatchTrace, RowTracer, Span,
                                           SpanRing, aggregate_stage_wires,
                                           fleet_stage_latency)

__all__ = [
    "AlertRule", "BatchTrace", "Counter", "Gauge", "Histogram",
    "IncidentRecorder", "MetricsRegistry", "RowTracer", "Sentinel", "Span",
    "SpanRing", "aggregate_stage_wires", "default_rule_pack",
    "fleet_rule_pack", "fleet_stage_latency", "leaf_paths", "load_rules",
    "metric_name", "parse_prometheus", "start_sentinel",
]
