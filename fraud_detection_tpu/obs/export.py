"""Metric/trace egress: atomic file publication, a scrape endpoint, and
JAX profiler capture windows.

Three small adapters from the in-process registry to the outside world:

* :func:`start_metrics_writer` — the ``--metrics-file`` dumper, the exact
  shape of serve's ``--health-file`` writer (periodic + final write,
  atomic publication via ``utils.atomicio``): a ``.prom``/``.txt`` path
  gets Prometheus text, anything else the JSON rendering.
* :class:`MetricsServer` — a ``--metrics-port`` stdlib HTTP endpoint
  (``/metrics`` Prometheus text, ``/metrics.json`` JSON) on a daemon
  thread; ``port=0`` binds an ephemeral port (tests read ``.port``).
  Scrapes are counted through the registry's own counter, so the exporter
  observes itself.
* :func:`start_profile_window` — an N-batch ``jax.profiler`` capture
  started when serving begins and stopped once the engine has delivered
  ``n_batches`` (or at shutdown): ``serve --profile-dir`` hands the
  TensorBoard/Perfetto trace of exactly the warmed steady state instead
  of a compile-noise-dominated whole run. Prewarm/ladder measurement gets
  its own capture via ``utils.tracing.device_trace`` at the call site.

Everything here follows the observability prime directive: failures are
logged/counted, never raised into serving.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from fraud_detection_tpu.obs.metrics import MetricsRegistry
from fraud_detection_tpu.utils import get_logger
from fraud_detection_tpu.utils.atomicio import (atomic_write_json,
                                                atomic_write_text)

log = get_logger("obs.export")


def write_metrics(path: str, registry: MetricsRegistry) -> bool:
    """One atomic metrics publication; format chosen by extension
    (``.prom``/``.txt`` -> Prometheus text, else JSON)."""
    if path.endswith((".prom", ".txt")):
        return atomic_write_text(path, registry.render_prometheus())
    return atomic_write_json(path, registry.render_json())


def start_metrics_writer(path: Optional[str], interval: float,
                         registry: MetricsRegistry) -> Callable[[], None]:
    """Periodic ``--metrics-file`` dumper; returns ``finish()`` which
    stops the thread and writes the FINAL state (call it on every exit
    path, like the health writer's). No-op when ``path`` is None."""
    if path is None:
        return lambda: None
    writes = registry.counter("metrics_file_writes",
                              "metrics-file publications")

    def dump() -> None:
        if write_metrics(path, registry):
            writes.inc()

    stop = threading.Event()

    def loop() -> None:
        while not stop.wait(interval):
            dump()

    thread = threading.Thread(target=loop, daemon=True,
                              name="metrics-writer")
    thread.start()

    def finish() -> None:
        stop.set()
        thread.join(timeout=5.0)
        dump()

    return finish


class MetricsServer:
    """Stdlib HTTP scrape endpoint for one registry (see module doc).

    ``healthz_fn`` (optional) wires the ``/healthz`` readiness endpoint:
    a zero-arg callable returning ``(ok, firing_names)`` — the sentinel's
    ``healthz()`` (obs/sentinel/engine.py). 200 with ``{"ok": true}``
    while no critical alert is firing, 503 with the firing rule names as
    JSON otherwise; scrapes self-count exactly like ``/metrics``. Without
    a sentinel the endpoint reports ready with ``"alerts": false`` so
    probers can tell "healthy" from "not watched"."""

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1", healthz_fn=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.registry = registry
        self.healthz_fn = healthz_fn
        scrapes = registry.counter("metrics_scrapes", "HTTP scrapes served")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib handler contract
                import json as _json

                status = 200
                if self.path.split("?", 1)[0] == "/metrics":
                    body = outer.registry.render_prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?", 1)[0] == "/metrics.json":
                    body = _json.dumps(outer.registry.render_json()).encode()
                    ctype = "application/json"
                elif self.path.split("?", 1)[0] == "/healthz":
                    fn = outer.healthz_fn
                    if fn is None:
                        doc = {"ok": True, "alerts": False, "firing": []}
                        ok = True
                    else:
                        try:
                            ok, firing = fn()
                        except Exception:  # noqa: BLE001 — probe must answer
                            ok, firing = True, []
                        doc = {"ok": bool(ok), "alerts": True,
                               "firing": list(firing)}
                    status = 200 if doc["ok"] else 503
                    body = _json.dumps(doc).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                scrapes.inc()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr spam
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="metrics-http")
        self._thread.start()

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:  # noqa: BLE001 — shutdown must never raise
            pass
        self._thread.join(timeout=5.0)


def start_profile_window(profile_dir: Optional[str], n_batches: int,
                         batches_fn: Callable[[], int], *,
                         poll_interval: float = 0.05
                         ) -> Callable[[], Optional[dict]]:
    """Capture a ``jax.profiler`` trace of the first ``n_batches``
    delivered batches (measured through ``batches_fn``, e.g.
    ``lambda: engine.stats.batches``). Returns ``finish()`` -> a small
    report dict (or None when disabled/failed); ``finish`` also stops the
    capture early at shutdown so a short run still leaves a valid trace.
    Zero-cost no-op when ``profile_dir`` is None."""
    if profile_dir is None:
        return lambda: None
    state = {"stopped": False, "error": None, "batches": 0}
    stop = threading.Event()
    lock = threading.Lock()

    def _stop_trace() -> None:
        with lock:
            if state["stopped"]:
                return
            state["stopped"] = True
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — profiling must never kill serving
            state["error"] = repr(e)

    try:
        import jax

        jax.profiler.start_trace(profile_dir)
    except Exception as e:  # noqa: BLE001
        log.warning("profiler trace unavailable: %r", e)
        return lambda: {"dir": profile_dir, "error": repr(e), "batches": 0}

    def watch() -> None:
        while not stop.wait(poll_interval):
            try:
                n = int(batches_fn())
            except Exception:  # noqa: BLE001
                n = 0
            state["batches"] = n
            if n >= n_batches:
                break
        _stop_trace()

    thread = threading.Thread(target=watch, daemon=True,
                              name="profile-window")
    thread.start()

    def finish() -> Optional[dict]:
        stop.set()
        thread.join(timeout=5.0)
        _stop_trace()
        return {"dir": profile_dir, "target_batches": n_batches,
                "batches": state["batches"], "error": state["error"]}

    return finish
