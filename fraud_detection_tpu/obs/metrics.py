"""Unified metrics surface: one registry, Prometheus text + JSON out.

Every subsystem already reports — but each through its own ``health()``
dict with its own shape, and the serve CLI, the fleet bus, and the bench
all re-plumb those dicts differently. This registry makes ONE schema out
of them:

* native instruments — :class:`Counter`, :class:`Gauge`, and
  :class:`Histogram` (a :class:`LatencySketch` behind a summary-style
  export) — for code that wants first-class metrics;
* **collectors** — zero-arg callables returning a (nested) health-style
  dict, flattened into metric samples at render time. Registering an
  engine's ``health`` as a collector maps EVERY existing health key into
  the exporter mechanically, so the exporter's key set is a superset of
  every ``health()`` block by construction (the FC301-style contract test
  in tests/test_obs.py pins it).

Flattening rules (deterministic, pinned by tests):

* nested dict keys join with ``_`` and are sanitized to the Prometheus
  charset;
* numbers export as-is, booleans as 0/1, ``None`` as ``NaN`` (the key
  stays visible — absence and unknown are different facts);
* strings become ``<name>{value="..."} 1`` info-style samples;
* lists export ``<name>_count`` (their length); lists of dicts recurse
  with an ``index`` label (the serve CLI's per-engine lists).

Rendering is pull-based: nothing in the hot path writes here — the
engine's counters live where they always lived, and a scrape/write walks
``health()`` exactly like the ``--health-file`` dumper does.
"""

from __future__ import annotations

import math
import re
import threading
import time
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from fraud_detection_tpu.sched.sketch import LatencySketch

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_ESC = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}

#: Quantiles exported for every histogram/sketch (summary convention).
QUANTILES = (0.5, 0.95, 0.99)


def sanitize(name: str) -> str:
    """A valid Prometheus metric-name fragment from any health key."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def metric_name(prefix: str, path: Tuple[str, ...]) -> str:
    """The ONE mapping from a health-dict key path to an exported metric
    name — the renderer and the superset contract test both use it, so
    they cannot drift."""
    return "_".join(sanitize(p) for p in (prefix, *path) if p)


def _esc_label(v: str) -> str:
    return "".join(_LABEL_ESC.get(c, c) for c in str(v))


def _fmt_labels(labels: Optional[Mapping[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{sanitize(k)}="{_esc_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    if isinstance(v, bool):
        return "1" if v else "0"
    return repr(float(v)) if isinstance(v, float) else str(v)


class Counter:
    """Monotonic counter (thread-safe)."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value: ``set()`` it, or give it a callback that is
    read at render time (the usual shape here — gauges over live state)."""

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help
        self._fn = fn
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — scrapes must never kill serving
                return float("nan")
        with self._lock:
            return self._value


class Histogram:
    """Quantile-sketch histogram over seconds-valued observations,
    exported summary-style (quantile labels + _sum + _count). Reuses the
    serving tree's :class:`LatencySketch` — bounded memory, lossless
    merge, the same ~7% relative bucket width everywhere."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.sketch = LatencySketch()

    def observe(self, sec: float) -> None:
        self.sketch.add(sec)

    def observe_many(self, secs) -> None:
        self.sketch.add_many(secs)


class MetricsRegistry:
    """The process-wide metric surface (see module docstring)."""

    def __init__(self, prefix: str = "fraud", *,
                 wall: Callable[[], float] = time.time):
        self.prefix = sanitize(prefix)
        self._wall = wall
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        # name -> (fn, constant labels); fn() returns a nested dict.
        self._collectors: Dict[str, Tuple[Callable[[], Optional[dict]],
                                          Optional[dict]]] = {}

    # -- registration (idempotent get-or-create) ------------------------

    def counter(self, name: str, help: str = "") -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name, help)
            return c

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, help, fn)
            return g

    def histogram(self, name: str, help: str = "") -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, help)
            return h

    def add_collector(self, name: str, fn: Callable[[], Optional[dict]], *,
                      labels: Optional[dict] = None) -> None:
        """Register a health-style dict source flattened at render time;
        re-registering a name replaces it (supervised engine rebuilds)."""
        with self._lock:
            self._collectors[name] = (fn, dict(labels) if labels else None)

    # -- flattening ------------------------------------------------------

    def _flatten(self, path: Tuple[str, ...], obj,
                 labels: Optional[dict],
                 out: List[Tuple[str, Optional[dict], float]]) -> None:
        name = metric_name(self.prefix, path)
        if isinstance(obj, dict):
            for k, v in obj.items():
                self._flatten(path + (str(k),), v, labels, out)
        elif isinstance(obj, bool):
            out.append((name, labels, 1.0 if obj else 0.0))
        elif isinstance(obj, (int, float)):
            out.append((name, labels, float(obj)))
        elif obj is None:
            out.append((name, labels, float("nan")))
        elif isinstance(obj, str):
            merged = dict(labels or {})
            merged["value"] = obj[:120]
            out.append((name, merged, 1.0))
        elif isinstance(obj, (list, tuple)):
            out.append((name + "_count", labels, float(len(obj))))
            if obj and all(isinstance(e, dict) for e in obj):
                for i, e in enumerate(obj):
                    merged = dict(labels or {})
                    merged["index"] = str(i)
                    self._flatten(path, e, merged, out)
        # anything else (bytes, objects) is silently unexportable

    def samples(self) -> List[Tuple[str, Optional[dict], float]]:
        """Every (name, labels, value) sample: native instruments first,
        then each collector's flattened dict."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._histograms.values())
            collectors = list(self._collectors.items())
        out: List[Tuple[str, Optional[dict], float]] = []
        for c in counters:
            out.append((metric_name(self.prefix, (c.name,)) + "_total",
                        None, c.value))
        for g in gauges:
            out.append((metric_name(self.prefix, (g.name,)), None, g.value))
        for h in hists:
            base = metric_name(self.prefix, (h.name,))
            snap = h.sketch
            for q in QUANTILES:
                v = snap.quantile(q)
                out.append((base, {"quantile": str(q)},
                            float("nan") if v is None else v))
            out.append((base + "_sum", None, snap.sum))
            out.append((base + "_count", None, float(snap.count)))
        for name, (fn, labels) in collectors:
            try:
                doc = fn()
            except Exception:  # noqa: BLE001 — scrapes must never kill serving
                doc = None
            if doc is None:
                continue
            self._flatten((name,), doc, labels, out)
        return out

    # -- rendering -------------------------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4). One line per sample; HELP/
        TYPE emitted once per metric name (everything untyped-gauge except
        native counters/histograms, which carry their own conventions)."""
        with self._lock:
            typed = {metric_name(self.prefix, (c.name,)) + "_total":
                     ("counter", c.help) for c in self._counters.values()}
            typed.update({metric_name(self.prefix, (h.name,)):
                          ("summary", h.help)
                          for h in self._histograms.values()})
        lines: List[str] = []
        seen: set = set()
        for name, labels, value in self.samples():
            base = name
            for suffix in ("_sum", "_count"):
                if name.endswith(suffix) and name[: -len(suffix)] in typed:
                    base = name[: -len(suffix)]
            if base not in seen:
                seen.add(base)
                kind, help_ = typed.get(base, ("gauge", ""))
                if help_:
                    lines.append(f"# HELP {base} {help_}")
                lines.append(f"# TYPE {base} {kind}")
            lines.append(f"{name}{_fmt_labels(labels)} {_fmt_value(value)}")
        lines.append("")
        return "\n".join(lines)

    def render_json(self) -> dict:
        """The same surface as JSON: raw collector dicts (the ONE nested
        schema) plus the flattened sample map — machine-joinable either
        way."""
        with self._lock:
            collectors = list(self._collectors.items())
        raw = {}
        for name, (fn, _) in collectors:
            try:
                raw[name] = fn()
            except Exception:  # noqa: BLE001
                raw[name] = None
        flat = {}
        for name, labels, value in self.samples():
            key = name + _fmt_labels(labels)
            flat[key] = None if (isinstance(value, float)
                                 and math.isnan(value)) else value
        return {"time": self._wall(), "collectors": raw, "metrics": flat}


# ---------------------------------------------------------------------------
# contract-test helpers (also used by the CI smoke)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(NaN|[-+0-9.eE]+|[-+]?Inf)$")


def parse_prometheus(text: str) -> Dict[str, List[Tuple[str, float]]]:
    """Strict-enough parser for the exposition format: every non-comment,
    non-blank line must match ``name{labels} value`` or the text is
    rejected (ValueError). Returns name -> [(label-blob, value)]."""
    out: Dict[str, List[Tuple[str, float]]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip() or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line {lineno}: {line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        out.setdefault(name, []).append((labels, float(value)))
    return out


def leaf_paths(obj, prefix: Tuple[str, ...] = ()) -> List[Tuple[str, ...]]:
    """Every leaf key path of a health-style dict — the contract test
    walks these through :func:`metric_name` and asserts each lands in the
    rendered output (list leaves map to their ``_count`` sample)."""
    if isinstance(obj, dict):
        out = []
        for k, v in obj.items():
            out.extend(leaf_paths(v, prefix + (str(k),)))
        return out
    return [prefix]
