"""Sentinel: burn-rate SLO alerting + incident flight recorder
(docs/observability.md "Alerting and incidents").

* sentinel/rules.py — declarative :class:`AlertRule`s (static thresholds,
  multi-window burn rate, ratios, deltas, absence/staleness) with
  hysteresis, the JSON rule-file parser, and the first-party default
  pack over the engine's ``health()`` plus the coordinator-level fleet
  pack;
* sentinel/engine.py — the :class:`Sentinel` evaluation engine: a
  flight-recorder ring of metric snapshots on an injectable clock, the
  pending→firing→resolved incident lifecycle with exact accounting, the
  serve-side "sentinel" thread driver, and the virtual-time drivers the
  scenario harness's ``detects_within`` gates run on;
* sentinel/bundle.py — the :class:`IncidentRecorder`: append-only
  ``incidents.jsonl`` plus per-incident bundle dirs (evidence window,
  flight-ring metric deltas, the full health block, forced-keep trace
  chains for implicated rows).
"""

from fraud_detection_tpu.obs.sentinel.bundle import (IncidentRecorder,
                                                     implicated_chains,
                                                     metric_deltas)
from fraud_detection_tpu.obs.sentinel.engine import (ChainedHealthSource,
                                                     Sentinel,
                                                     VirtualCadence,
                                                     evaluate_timeline,
                                                     start_sentinel)
from fraud_detection_tpu.obs.sentinel.rules import (AlertRule,
                                                    default_rule_pack,
                                                    fleet_rule_pack,
                                                    load_rules, parse_rules,
                                                    resolve_path)

__all__ = [
    "AlertRule", "ChainedHealthSource", "IncidentRecorder", "Sentinel",
    "VirtualCadence",
    "default_rule_pack", "evaluate_timeline", "fleet_rule_pack",
    "implicated_chains", "load_rules", "metric_deltas", "parse_rules",
    "resolve_path", "start_sentinel",
]
