"""Incident flight recorder: bundles written at every alert transition.

An alert that fires at 3am is only useful if the evidence that fired it is
still on disk at 9am. The :class:`IncidentRecorder` captures that evidence
at the moment of the transition, while the flight-recorder ring still holds
it:

* ``incidents.jsonl`` — one append-only line per transition (``fired`` /
  ``resolved``), written as a single ``write()`` of one newline-terminated
  JSON document under the recorder's lock, so concurrent sentinels sharing
  a directory interleave whole records, never bytes. The file is the
  machine-readable incident timeline (the CI alert-smoke parses it).
* ``<dir>/<incident id>/bundle.json`` — the per-incident postmortem bundle,
  published via the shared atomic writer: the rule (full spec + the
  observed value), the **evidence window** (every ``(stamp, value)`` the
  rule evaluated over its window), the **metric deltas** of the flight
  ring (numeric leaves: oldest vs newest snapshot, so "what moved while
  this fired" is one diff), the full latest health snapshot, and — when a
  row tracer is attached — the **forced-keep trace chains** of recently
  implicated rows (shed/DLQ'd/aborted/flagged events still in the span
  ring), each a complete poll→terminal chain by correlation id.
* resolution updates the incident's ``resolution.json`` next to the bundle
  (the original bundle stays byte-stable — a postmortem artifact must not
  mutate under the reader).

Failures follow the observability prime directive: recording returns
False/None and counts, never raises into the evaluation loop.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from fraud_detection_tpu.utils import get_logger
from fraud_detection_tpu.utils.atomicio import atomic_write_json

log = get_logger("obs.sentinel")

#: Row-event stages whose cids implicate rows in an incident (obs/trace.py
#: vocabulary): accountability events are forced-keeps, so their chains are
#: still in the ring when the alert fires.
_IMPLICATING = ("shed", "dlq", "abort", "flag", "annotate")


def metric_deltas(old: dict, new: dict, prefix: str = "") -> Dict[str, float]:
    """Numeric-leaf deltas between two health-shaped snapshots (dotted
    keys). Only leaves present in BOTH snapshots and actually moved are
    reported — the bundle answers "what changed", not "what exists"."""
    out: Dict[str, float] = {}
    if not isinstance(old, dict) or not isinstance(new, dict):
        return out
    for key, nv in new.items():
        path = f"{prefix}.{key}" if prefix else str(key)
        ov = old.get(key)
        if isinstance(nv, dict) and isinstance(ov, dict):
            out.update(metric_deltas(ov, nv, path))
        elif (isinstance(nv, (int, float)) and not isinstance(nv, bool)
              and isinstance(ov, (int, float)) and not isinstance(ov, bool)):
            d = nv - ov
            if d != 0:
                out[path] = round(float(d), 6)
    return out


def implicated_chains(rowtrace, *, max_chains: int = 8,
                      max_spans: int = 64) -> List[dict]:
    """The forced-keep chains of recently implicated rows: walk the span
    ring newest-first for accountability row events, then pull each cid's
    full chain. Bounded both ways — a bundle is a postmortem aid, not a
    ring dump."""
    if rowtrace is None:
        return []
    try:
        spans = rowtrace.ring.snapshot()
    except Exception:  # noqa: BLE001 — recording must never raise
        return []
    chains: List[dict] = []
    seen: set = set()
    for span in reversed(spans):
        if span.stage not in _IMPLICATING or span.cid in seen:
            continue
        seen.add(span.cid)
        chain = rowtrace.chain(span.cid)
        chains.append({
            "cid": span.cid,
            "event": span.stage,
            "detail": span.detail,
            "chain": [s.as_dict() for s in chain[:max_spans]],
        })
        if len(chains) >= max_chains:
            break
    return chains


class IncidentRecorder:
    """Append-only incident log + per-incident bundle dirs (module doc)."""

    def __init__(self, dir: str, *, rowtrace=None, ring_keep: int = 8):
        self.dir = dir
        self.rowtrace = rowtrace
        self.ring_keep = ring_keep      # flight-ring snapshots kept per bundle
        self.recorded = 0               # transitions appended to the log
        self.record_errors = 0
        self._lock = threading.Lock()
        os.makedirs(dir, exist_ok=True)

    @property
    def log_path(self) -> str:
        return os.path.join(self.dir, "incidents.jsonl")

    def _count_error(self) -> None:
        with self._lock:
            self.record_errors += 1

    def _append(self, record: dict) -> bool:
        """One transition line, appended whole (single write + flush)."""
        try:
            line = json.dumps(record) + "\n"
        except (TypeError, ValueError):
            self._count_error()
            return False
        with self._lock:
            try:
                with open(self.log_path, "a", encoding="utf-8") as f:
                    f.write(line)
                    f.flush()
                self.recorded += 1
                return True
            except OSError:
                self.record_errors += 1
                return False

    # ------------------------------------------------------------------
    # transitions (called by the sentinel OUTSIDE its state lock)
    # ------------------------------------------------------------------

    def record_fired(self, incident: dict, rule: dict,
                     evidence_window: Sequence[Tuple[float, object]],
                     ring: Sequence[Tuple[float, dict]]) -> Optional[str]:
        """Capture the bundle for a newly FIRING incident; returns the
        bundle dir (or None on failure). ``ring`` is the sentinel's
        flight-recorder snapshot ring, oldest → newest."""
        self._append({"event": "fired", **incident})
        bundle_dir = os.path.join(self.dir, incident["id"])
        try:
            os.makedirs(bundle_dir, exist_ok=True)
        except OSError:
            self._count_error()
            return None
        recent = list(ring)[-self.ring_keep:]
        bundle = {
            "incident": incident,
            "rule": rule,
            # The values the rule actually judged, stamped in the
            # sentinel's clock domain (virtual seconds under the
            # scenario harness).
            "evidence_window": [{"t": round(t, 6), "value": v}
                                for t, v in evidence_window],
            "ring": {
                "snapshots": len(recent),
                "span_s": (round(recent[-1][0] - recent[0][0], 6)
                           if len(recent) > 1 else 0.0),
                "deltas": (metric_deltas(recent[0][1], recent[-1][1])
                           if len(recent) > 1 else {}),
            },
            "health": recent[-1][1] if recent else None,
            "chains": implicated_chains(self.rowtrace),
        }
        if not atomic_write_json(os.path.join(bundle_dir, "bundle.json"),
                                 bundle):
            self._count_error()
            log.warning("incident bundle write failed: %s", bundle_dir)
            return None
        return bundle_dir

    def record_resolved(self, incident: dict,
                        ring: Sequence[Tuple[float, dict]]) -> None:
        """Log the resolution and publish ``resolution.json`` beside the
        (immutable) firing bundle."""
        self._append({"event": "resolved", **incident})
        bundle_dir = os.path.join(self.dir, incident["id"])
        if os.path.isdir(bundle_dir):
            recent = list(ring)[-self.ring_keep:]
            atomic_write_json(os.path.join(bundle_dir, "resolution.json"), {
                "incident": incident,
                "health": recent[-1][1] if recent else None,
            })

    def record_scale(self, decision: dict,
                     evidence_window: Sequence[Tuple[float, object]] = ()
                     ) -> bool:
        """One autoscale decision (fleet/autoscale/) on the SAME
        append-only timeline the alert transitions use, with the evidence
        the policy judged — "why did the fleet grow at 3am" reads next to
        the alert that caused it, in one ``incidents.jsonl``."""
        return self._append({
            "event": "scale", **decision,
            "evidence_window": [{"t": round(t, 6), "value": v}
                                for t, v in evidence_window]})

    def snapshot(self) -> dict:
        with self._lock:
            return {"dir": self.dir, "recorded": self.recorded,
                    "errors": self.record_errors}
