"""Sentinel: the alerting engine — rule evaluation, incident lifecycle.

PR 10 built the telemetry plane and PR 12 the offline judge; this module is
the part of the running system that watches itself. One :class:`Sentinel`
owns:

* a **flight-recorder ring** of timestamped metric snapshots (the nested
  ``health()``-shaped dict its ``source`` callable returns), appended once
  per evaluation — the window store every burn-rate/delta/stale rule reads
  and the evidence the incident bundles capture;
* a **rule table** (obs/sentinel/rules.py) evaluated on every pass;
* the **incident lifecycle**: ok → pending (condition observed) → firing
  (held ``for_s``) → resolved (clear ``resolve_s``), with exact accounting
  (``fired == resolved + still_firing`` is a pinned invariant — the chaos
  suite asserts it across supervised restart chains);
* the **transition hooks**: every fire/resolve appends to the recorder's
  append-only ``incidents.jsonl`` and captures a bundle
  (obs/sentinel/bundle.py).

Time is INJECTABLE and one-dimensional: ``clock()`` stamps evaluations,
windows, and hysteresis alike, so the same sentinel runs on wall time under
serve (:func:`start_sentinel`'s thread) and on *virtual* time under the
scenario harness (:class:`VirtualCadence` /
:func:`evaluate_timeline`) — a warp-paced game day (time_scale 0) evaluates
rules at exactly the virtual times a real-time run would, which is what
makes ``detects_within`` SLO gates deterministic (the warp-vs-paced
regression test in tests/test_sentinel.py pins it).

Thread model: ``evaluate()`` runs on whichever single thread drives this
sentinel (the serve "sentinel" thread, the scenario driver, a fleet
worker's poll path, the fleet monitor tick); ``snapshot()``/``firing()``/
``healthz()`` are the cross-thread surface. All mutable state sits under
one lock; the source pull and the recorder's file I/O happen OUTSIDE it,
so the sentinel never holds its lock across another subsystem's.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from fraud_detection_tpu.obs.sentinel.bundle import IncidentRecorder
from fraud_detection_tpu.obs.sentinel.rules import AlertRule
from fraud_detection_tpu.utils import get_logger

log = get_logger("obs.sentinel")

#: Evidence-window samples kept per rule (what the bundle's
#: ``evidence_window`` shows: the last observed values the rule judged).
_EVIDENCE_KEEP = 32
#: Compact incident records kept in ``snapshot()["incidents"]``.
_INCIDENTS_KEEP = 64


class _RuleState:
    """One rule's lifecycle state (sentinel-lock protected)."""

    __slots__ = ("rule", "state", "pending_since", "clear_since",
                 "incident", "evidence")

    def __init__(self, rule: AlertRule):
        self.rule = rule
        self.state = "ok"                   # ok | pending | firing
        self.pending_since: Optional[float] = None
        self.clear_since: Optional[float] = None
        self.incident: Optional[dict] = None
        self.evidence: deque = deque(maxlen=_EVIDENCE_KEEP)


class Sentinel:
    """Rule evaluation + incident lifecycle over one metric source."""

    def __init__(self, source: Callable[[], Optional[dict]],
                 rules: Sequence[AlertRule], *,
                 clock: Callable[[], float] = time.monotonic,
                 recorder: Optional[IncidentRecorder] = None,
                 worker: str = "w0", history: int = 256):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {names}")
        if history < 2:
            raise ValueError(f"history must be >= 2, got {history}")
        self.source = source
        self.rules: Tuple[AlertRule, ...] = tuple(rules)
        self.clock = clock
        self.recorder = recorder
        self.worker = worker
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=history)   # (stamp, snapshot)
        self._states: Dict[str, _RuleState] = {
            r.name: _RuleState(r) for r in self.rules}
        self._incidents: deque = deque(maxlen=_INCIDENTS_KEEP)
        self._seq = 0
        self.evaluations = 0
        self.eval_errors = 0
        self.fired = 0
        self.resolved = 0
        self._last_eval_at: Optional[float] = None

    # ------------------------------------------------------------------
    # evaluation (single driver thread)
    # ------------------------------------------------------------------

    def prime(self, now: Optional[float] = None) -> None:
        """Seed the flight ring with a baseline snapshot at ``now``
        WITHOUT advancing rule lifecycles — the source's current state,
        or an EMPTY baseline when the source isn't up yet (missing
        counters read as 0 in window deltas). Without this, everything
        that happened before the first periodic evaluation is absorbed
        into its snapshot and window deltas read zero: a burn already in
        progress at the first tick must be visible AS a burn. The
        drivers (start_sentinel) prime automatically."""
        now = self.clock() if now is None else now
        try:
            snap = self.source()
        except Exception:  # noqa: BLE001
            snap = None
        with self._lock:
            if not self._ring:
                self._ring.append((now, snap if isinstance(snap, dict)
                                   else {}))

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass: pull the source, append to the flight
        ring, advance every rule's lifecycle. Returns the transitions
        (``{"event": "fired"/"resolved", ...}`` incident records) this
        pass produced. Source failures count in ``eval_errors`` and skip
        the pass — a broken health() must not read as 'all clear' forever,
        so the absence rule still sees a missing-source pass as a tick
        with NO fresh snapshot (the ring keeps its last state)."""
        now = self.clock() if now is None else now
        try:
            snap = self.source()
        except Exception:  # noqa: BLE001 — alerting must never kill serving
            snap = None
        transitions: List[dict] = []
        bundles: List[tuple] = []       # (kind, incident, state, ring copy)
        with self._lock:
            self.evaluations += 1
            self._last_eval_at = now
            if not isinstance(snap, dict):
                self.eval_errors += 1
                return []
            self._ring.append((now, snap))
            ring = tuple(self._ring)
            for state in self._states.values():
                t = self._advance_locked(state, ring, now)
                if t is not None:
                    transitions.append(t)
                    bundles.append((t["event"], t, state, ring))
        # Recorder I/O outside the lock (bundle.py owns its own lock).
        if self.recorder is not None:
            for kind, incident, state, ring in bundles:
                incident = {k: v for k, v in incident.items()
                            if k != "event"}
                if kind == "fired":
                    self.recorder.record_fired(
                        incident, state.rule.as_dict(),
                        list(state.evidence), ring)
                else:
                    self.recorder.record_resolved(incident, ring)
        return transitions

    def _advance_locked(self, st: _RuleState, ring, now: float
                        ) -> Optional[dict]:
        cond, observed = st.rule.condition(ring, now)
        if cond:
            st.evidence.append((now, observed))
        if st.state == "ok":
            if not cond:
                return None
            st.state = "pending"
            st.pending_since = now
            # falls through: for_s == 0 fires on the same pass
        if st.state == "pending":
            if not cond:
                st.state = "ok"
                st.pending_since = None
                return None
            if now - st.pending_since < st.rule.for_s:
                return None
            st.state = "firing"
            st.clear_since = None
            self._seq += 1
            self.fired += 1
            incident = {
                "id": f"{self.worker}-i{self._seq:04d}-{st.rule.name}",
                "rule": st.rule.name,
                "severity": st.rule.severity,
                "worker": self.worker,
                "fired_at": round(now, 6),
                "pending_since": round(st.pending_since, 6),
                "value": observed,
                "resolved_at": None,
            }
            st.incident = incident
            self._incidents.append(incident)
            log.warning("alert FIRING: %s (%s) value=%r",
                        st.rule.name, st.rule.severity, observed)
            return {"event": "fired", **incident}
        # firing
        if cond:
            st.clear_since = None
            return None
        if st.clear_since is None:
            st.clear_since = now
        if now - st.clear_since < st.rule.resolve_s:
            return None
        st.state = "ok"
        st.pending_since = None
        self.resolved += 1
        incident = dict(st.incident or {})
        incident["resolved_at"] = round(now, 6)
        incident["duration_s"] = round(
            now - incident.get("fired_at", now), 6)
        # The shared deque entry updates in place: snapshot() readers see
        # the incident resolve without a second record.
        if st.incident is not None:
            st.incident["resolved_at"] = incident["resolved_at"]
        st.incident = None
        st.clear_since = None
        log.info("alert resolved: %s", st.rule.name)
        return {"event": "resolved", **incident}

    # ------------------------------------------------------------------
    # cross-thread surface
    # ------------------------------------------------------------------

    def firing(self) -> List[str]:
        """Names of rules currently firing (sorted)."""
        with self._lock:
            return sorted(n for n, s in self._states.items()
                          if s.state == "firing")

    def last_eval_at(self) -> Optional[float]:
        """Stamp of the newest evaluation, in this sentinel's clock
        domain (VIRTUAL seconds under the scenario harness) — the time
        base the autoscaler shares so scale decisions are stamped in the
        same domain as the signals that caused them (fleet/autoscale/)."""
        with self._lock:
            return self._last_eval_at

    def critical_firing(self) -> List[str]:
        """Firing rules whose severity is critical — the /healthz gate."""
        with self._lock:
            return sorted(n for n, s in self._states.items()
                          if s.state == "firing"
                          and s.rule.severity == "critical")

    def healthz(self) -> Tuple[bool, List[str]]:
        """Readiness verdict: (ok, critical firing rule names)."""
        crit = self.critical_firing()
        return (not crit, crit)

    def snapshot(self) -> dict:
        """The ``alerts`` health block (schema pinned in
        tests/test_sentinel.py ALERTS_BLOCK_SCHEMA, FC301-checked).
        ``fired == resolved + still_firing`` is the accounting invariant
        the chaos suite pins."""
        with self._lock:
            firing = sorted(n for n, s in self._states.items()
                            if s.state == "firing")
            pending = sorted(n for n, s in self._states.items()
                             if s.state == "pending")
            critical = sorted(
                n for n, s in self._states.items()
                if s.state == "firing" and s.rule.severity == "critical")
            incidents = [dict(i) for i in self._incidents]
            return {
                "worker": self.worker,
                "rules": len(self.rules),
                "evaluations": self.evaluations,
                "eval_errors": self.eval_errors,
                "last_eval_at": self._last_eval_at,
                "ring_depth": len(self._ring),
                "firing": firing,
                "critical_firing": critical,
                "pending": pending,
                "fired": self.fired,
                "resolved": self.resolved,
                "still_firing": len(firing),
                "incidents": incidents,
                "recorder": (self.recorder.snapshot()
                             if self.recorder is not None else None),
            }


class ChainedHealthSource:
    """Cumulative health across a supervised incarnation chain.

    Engine counters reset when the supervisor rebuilds an incarnation,
    which breaks alerting two ways: a window delta spanning the restart
    reads the reset as "restarted from zero" (losing the dead
    incarnation's tail), and a short-lived signal — one ``commits_skipped``
    on a flush failure an instant before the engine dies — only exists in
    a snapshot the sentinel probably never samples. This source folds each
    dead incarnation's final counters into an accumulator at ``attach``
    time (the same place the supervisor's ``make_engine`` shares the DLQ
    poison tracker), so the sentinel sees MONOTONIC chain-cumulative
    counters plus a ``supervisor`` block whose ``restarts`` counter feeds
    the restart-churn rule.

    Single-writer: ``attach`` runs on the supervisor path; ``__call__``
    on the sentinel driver. The accumulator is only mutated under the
    lock, and health reads stay lock-free racy samples as everywhere.
    """

    COUNTERS = ("processed", "malformed", "dead_lettered", "shed",
                "rebalanced_commits", "commits_skipped")

    def __init__(self):
        self._lock = threading.Lock()
        self._acc = {k: 0 for k in self.COUNTERS}
        self._live = None
        self._builds = 0

    def attach(self, engine) -> None:
        """Declare a new live incarnation; the previous one's counters
        fold into the accumulator."""
        with self._lock:
            prev = self._live
            if prev is not None:
                stats = prev.stats
                for k in self.COUNTERS:
                    self._acc[k] += getattr(stats, k, 0)
            self._live = engine
            self._builds += 1

    def __call__(self) -> Optional[dict]:
        with self._lock:
            engine = self._live
            acc = dict(self._acc)
            builds = self._builds
        if engine is None:
            return None
        h = engine.health()
        for k in self.COUNTERS:
            v = h.get(k)
            if isinstance(v, (int, float)):
                h[k] = v + acc[k]
        h["supervisor"] = {"restarts": max(builds - 1, 0)}
        return h


# ---------------------------------------------------------------------------
# drivers: wall-cadence thread (serve) and virtual-time cadence (scenarios)
# ---------------------------------------------------------------------------

def start_sentinel(sentinels: Sequence[Sentinel], interval: float,
                   *, wall_sleep_floor: float = 0.002
                   ) -> Callable[[], None]:
    """The serve-side driver: ONE daemon thread ("sentinel") evaluating
    every sentinel each ``interval`` seconds; returns ``finish()`` which
    stops the thread and runs a FINAL evaluation pass so the exit stats
    reflect the run's last state (same contract as the metrics writer).
    No-op when ``sentinels`` is empty."""
    sentinels = list(sentinels)
    if not sentinels:
        return lambda: None
    if interval <= 0:
        raise ValueError(f"interval must be > 0, got {interval}")
    for s in sentinels:
        s.prime()       # baseline BEFORE traffic: burns measure from 0
    stop = threading.Event()

    def loop() -> None:
        while not stop.wait(max(interval, wall_sleep_floor)):
            for s in sentinels:
                s.evaluate()

    thread = threading.Thread(target=loop, daemon=True, name="sentinel")
    thread.start()

    def finish() -> None:
        stop.set()
        thread.join(timeout=5.0)
        for s in sentinels:
            s.evaluate()

    return finish


class VirtualCadence:
    """A sentinel clock for scenario runs: reads the scenario clock's
    VIRTUAL time, but never stalls — each call advances at least ``step``
    past the last reading, so hysteresis windows keep elapsing while the
    engine drains a warp-fed backlog (the feeder's cursor stops at the
    timeline's end; drain-side evaluations then advance one virtual tick
    each, which is what makes ``detects_within`` measure real evaluation
    latency in warp mode instead of freezing at the end stamp).

    Single-caller by contract (the one sentinel driver thread)."""

    def __init__(self, now_fn: Callable[[], float], step: float):
        if step <= 0:
            raise ValueError(f"step must be > 0, got {step}")
        self.now_fn = now_fn
        self.step = step
        self._last = -step

    def __call__(self) -> float:
        v = max(self.now_fn(), self._last + self.step)
        self._last = v
        return v


def evaluate_timeline(sentinel: Sentinel, clock, until_s: float,
                      interval_s: float) -> List[dict]:
    """Deterministically evaluate a sentinel at virtual times 0,
    ``interval_s``, 2·``interval_s``, … ``until_s`` on a
    :class:`~fraud_detection_tpu.scenarios.clock.ScenarioClock` — in warp
    mode (time_scale 0) this is instant, in paced mode ``advance_to``
    sleeps the gaps out; either way the EVALUATION TIMELINE is identical,
    which the warp-vs-paced regression test pins. Returns every transition
    in order."""
    if interval_s <= 0:
        raise ValueError(f"interval_s must be > 0, got {interval_s}")
    transitions: List[dict] = []
    t = 0.0
    while t <= until_s + 1e-9:
        clock.advance_to(t)
        transitions.extend(sentinel.evaluate(now=t))
        t += interval_s
    return transitions
