"""Declarative alert rules: what "this is an incident" means, as data.

An :class:`AlertRule` names a condition over the periodic metric snapshots
the sentinel records (obs/sentinel/engine.py) — dotted paths into the same
nested ``health()``-shaped dicts the exporter flattens, so anything a
dashboard can read, a rule can alert on. Five rule kinds cover the failure
vocabulary the codebase actually models:

* ``static`` — the value at ``path`` compared against ``limit`` (the p99
  SLO burn, the dispatch-stall age, the spans_open leak). ``while_path``
  optionally gates the condition on another truthy value (stall only
  matters while ``running``).
* ``burn_rate`` — multi-window budget burn over two CUMULATIVE counters:
  the ratio of ``num``/``den`` deltas must exceed ``limit`` over BOTH the
  fast window (catches the spike) and the slow window (confirms it is not
  a blip) — the classic two-window burn-rate alert, with the windows read
  from the sentinel's snapshot ring instead of a TSDB. Counter resets
  (supervised engine restarts) are handled the way Prometheus ``rate()``
  does: a negative delta reads as "restarted from zero".
* ``ratio`` — instantaneous ratio of two cumulative counters (the
  explain-coverage gauge: explained-or-accounted over submitted).
  ``num``/``den`` accept ``+``-joined path lists, summed; honors
  ``while_path`` (fleet idleness only matters once traffic has flowed).
* ``delta`` — the change of a counter over the fast window compared
  against ``limit`` (breaker opens, fence/zombie commit events, worker
  count drops — a NEGATIVE limit with ``op="<="`` alerts on decrease);
  honors ``while_path`` (a membership drop only alerts while committed
  work remains). A decrease-watching delta judges the drop from the
  window's HIGH-WATER mark, not the far-edge sample: the window can
  reach back to a sample taken before the watched gauge finished
  forming (a sentinel primed mid-group-formation records membership 1),
  and a far-edge comparison would read a later real 2 → 1 death as 0.
  Growth inside the window must never mask a drop.
* ``absence`` / ``stale`` — the path is missing/None (a subsystem stopped
  reporting), or a counter has not moved across the fast window while
  ``while_path`` is truthy (progress stalled while work remains).

Every rule carries hysteresis: the condition must hold ``for_s`` seconds
(sentinel-clock seconds — virtual seconds under the scenario harness)
before the incident FIRES, and must stay clear ``resolve_s`` seconds
before it RESOLVES, so a flapping metric produces one incident, not a
storm. ``severity`` ("warning" | "critical") decides whether a firing
rule flips the ``/healthz`` readiness endpoint to 503.

Rules parse from JSON (serve ``--alert-rules FILE``) and
:func:`default_rule_pack` declares the first-party pack covering the
failure modes the tree models end to end (docs/observability.md
"Alerting and incidents" documents each rule's rationale).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple, Union

Number = Union[int, float]

KINDS = ("static", "burn_rate", "ratio", "delta", "absence", "stale")
SEVERITIES = ("warning", "critical")

_OPS = {
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
}


def resolve_path(snapshot, path: str) -> Tuple[bool, object]:
    """Walk a dotted path into a nested snapshot dict; ``+``-joined paths
    sum their (numeric) leaves — missing/None terms read as the whole
    path missing, so a half-reported sum can never alert on garbage.
    Returns (found, value)."""
    if "+" in path:
        total = 0.0
        for part in path.split("+"):
            found, v = resolve_path(snapshot, part.strip())
            if not found or not isinstance(v, (int, float)) \
                    or isinstance(v, bool):
                return False, None
            total += v
        return True, total
    node = snapshot
    for part in path.split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        elif isinstance(node, (list, tuple)) and part.isdigit() \
                and int(part) < len(node):
            node = node[int(part)]
        else:
            return False, None
    return (node is not None), node


@dataclass(frozen=True)
class AlertRule:
    """One declared alert (see module docstring for the kind semantics)."""

    name: str
    kind: str = "static"
    path: str = ""              # static/delta/absence/stale value path
    num: str = ""               # burn_rate/ratio numerator ('+'-joined sums)
    den: str = ""               # burn_rate/ratio denominator
    op: str = ">"               # comparison for static/ratio/delta
    limit: Number = 0.0
    severity: str = "critical"
    for_s: float = 0.0          # condition must hold this long to FIRE
    resolve_s: float = 0.0      # must stay clear this long to RESOLVE
    fast_s: float = 30.0        # fast window (burn_rate/delta/stale)
    slow_s: float = 120.0       # slow confirm window (burn_rate)
    min_den: float = 1.0        # burn_rate/ratio: denominator floor below
                                # which the rule abstains (no traffic, no
                                # ratio — an idle stream must not alert)
    while_path: str = ""        # truthy gate (static/delta/stale)
    description: str = ""

    def __post_init__(self):
        if not self.name:
            raise ValueError("alert rule needs a name")
        if self.kind not in KINDS:
            raise ValueError(
                f"rule {self.name!r}: unknown kind {self.kind!r} "
                f"(one of {KINDS})")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"rule {self.name!r}: severity must be one of "
                f"{SEVERITIES}, got {self.severity!r}")
        if self.op not in _OPS:
            raise ValueError(
                f"rule {self.name!r}: op must be one of {sorted(_OPS)}, "
                f"got {self.op!r}")
        if self.kind in ("burn_rate", "ratio"):
            if not self.num or not self.den:
                raise ValueError(
                    f"rule {self.name!r}: kind {self.kind!r} needs "
                    f"num and den counter paths")
        elif not self.path:
            raise ValueError(
                f"rule {self.name!r}: kind {self.kind!r} needs a path")
        if self.kind == "burn_rate" and self.slow_s < self.fast_s:
            raise ValueError(
                f"rule {self.name!r}: slow_s ({self.slow_s}) must be >= "
                f"fast_s ({self.fast_s})")
        for field_name in ("for_s", "resolve_s"):
            if getattr(self, field_name) < 0:
                raise ValueError(
                    f"rule {self.name!r}: {field_name} must be >= 0")
        if self.fast_s <= 0 or self.slow_s <= 0:
            raise ValueError(
                f"rule {self.name!r}: windows must be > 0 "
                f"(fast_s={self.fast_s}, slow_s={self.slow_s})")

    # -- evaluation ------------------------------------------------------

    def condition(self, ring: Sequence[Tuple[float, dict]],
                  now: float) -> Tuple[bool, object]:
        """Evaluate against the sentinel's snapshot ring (oldest → newest,
        ``(stamp, snapshot)`` pairs; the newest entry is the CURRENT
        snapshot at ``now``). Returns (condition_true, observed_value) —
        the observed value lands in the incident record as evidence."""
        if not ring:
            return False, None
        _, cur = ring[-1]
        if self.kind == "static":
            if not self._while_ok(cur):
                return False, None
            found, v = resolve_path(cur, self.path)
            if not found or not isinstance(v, (int, float)):
                return False, None
            return _OPS[self.op](v, self.limit), v
        if self.kind == "ratio":
            if not self._while_ok(cur):
                return False, None
            found_n, n = resolve_path(cur, self.num)
            found_d, d = resolve_path(cur, self.den)
            if not found_n or not found_d or not isinstance(n, (int, float)) \
                    or not isinstance(d, (int, float)) or d < self.min_den:
                return False, None
            ratio = n / d
            return _OPS[self.op](ratio, self.limit), round(ratio, 6)
        if self.kind == "absence":
            found, _ = resolve_path(cur, self.path)
            return not found, None
        if self.kind == "delta":
            if not self._while_ok(cur):
                return False, None
            d = self._window_delta(ring, now, self.path, self.fast_s,
                                   reset_guard=self.op in (">", ">="),
                                   from_peak=self.op in ("<", "<="))
            if d is None:
                return False, None
            return _OPS[self.op](d, self.limit), d
        if self.kind == "stale":
            if not self._while_ok(cur):
                return False, None
            # Stale means the counter did not move over the WHOLE window —
            # only judged once the ring actually spans it (the short-
            # history fallback would otherwise declare staleness from two
            # snapshots milliseconds apart).
            oldest = self._at_or_before(ring, now - self.fast_s)
            if oldest is None or ring[-1][0] - oldest[0] < self.fast_s:
                return False, None
            d = self._window_delta(ring, now, self.path, self.fast_s,
                                   reset_guard=False)
            if d is None:
                return False, None
            return d == 0, d
        # burn_rate: both windows' delta ratios must exceed the limit.
        fast = self._window_ratio(ring, now, self.fast_s)
        slow = self._window_ratio(ring, now, self.slow_s)
        if fast is None or slow is None:
            return False, None
        fired = (_OPS[self.op](fast, self.limit)
                 and _OPS[self.op](slow, self.limit))
        return fired, {"fast": round(fast, 6), "slow": round(slow, 6)}

    def _while_ok(self, cur: dict) -> bool:
        if not self.while_path:
            return True
        found, v = resolve_path(cur, self.while_path)
        return bool(found and v)

    @staticmethod
    def _at_or_before(ring: Sequence[Tuple[float, dict]],
                      stamp: float) -> Optional[Tuple[float, dict]]:
        """Newest ring entry at or older than ``stamp`` — the window's far
        edge. None when the ring's history doesn't reach back that far AND
        has no genuinely-older entry (then the oldest entry stands in, so
        short runs still evaluate over the span they actually have)."""
        best = None
        for entry in ring:
            if entry[0] <= stamp:
                best = entry
            else:
                break
        if best is None and len(ring) > 1:
            best = ring[0]      # window exceeds history: whole span
        return best

    def _window_delta(self, ring, now: float, path: str,
                      window_s: float, *,
                      reset_guard: bool = True,
                      from_peak: bool = False) -> Optional[float]:
        old = self._at_or_before(ring, now - window_s)
        if old is None:
            return None
        found_old, v_old = resolve_path(old[1], path)
        found_cur, v_cur = resolve_path(ring[-1][1], path)
        if not found_cur or not isinstance(v_cur, (int, float)):
            return None
        if not found_old or not isinstance(v_old, (int, float)):
            v_old = 0.0         # the counter appeared mid-window
        if from_peak:
            # Decrease-watching gauge (module docstring): the drop is
            # judged from the window's high-water mark, so a far edge
            # that predates the gauge's formation (membership sampled
            # mid-group-settlement) cannot mask a real drop. The current
            # sample participates: if it IS the peak, the delta is 0.
            peak = float(v_old)
            for stamp, snap in ring:
                if stamp < old[0]:
                    continue
                found, v = resolve_path(snap, path)
                if found and isinstance(v, (int, float)) and float(v) > peak:
                    peak = float(v)
            v_old = peak
        d = float(v_cur) - float(v_old)
        # Counter reset (supervised restart): rate() semantics — the
        # counter restarted from zero, so the post-reset value IS the
        # delta. Applied only when the rule watches for INCREASES: a
        # decrease-watching delta (worker_absence's membership drop) is
        # watching a gauge, where a negative delta is the signal itself.
        return float(v_cur) if (reset_guard and d < 0) else d

    def _window_ratio(self, ring, now: float,
                      window_s: float) -> Optional[float]:
        dn = self._window_delta(ring, now, self.num, window_s)
        dd = self._window_delta(ring, now, self.den, window_s)
        if dn is None or dd is None or dd < self.min_den:
            return None
        return dn / dd

    def as_dict(self) -> dict:
        return {"name": self.name, "kind": self.kind, "path": self.path,
                "num": self.num, "den": self.den, "op": self.op,
                "limit": self.limit, "severity": self.severity,
                "for_s": self.for_s, "resolve_s": self.resolve_s,
                "fast_s": self.fast_s, "slow_s": self.slow_s,
                "while_path": self.while_path,
                "description": self.description}


def parse_rules(obj) -> Tuple[AlertRule, ...]:
    """Rules from parsed JSON: a list of rule dicts, or ``{"rules": [...]}``.
    Unknown fields are rejected (a typo'd threshold must not silently
    become the default)."""
    if isinstance(obj, dict):
        obj = obj.get("rules")
    if not isinstance(obj, list):
        raise ValueError("alert rules must be a JSON list of rule objects "
                         "(or {'rules': [...]})")
    valid = {f for f in AlertRule.__dataclass_fields__}  # noqa: C416
    out: List[AlertRule] = []
    for i, item in enumerate(obj):
        if not isinstance(item, dict):
            raise ValueError(f"rule #{i} is not an object: {item!r}")
        unknown = set(item) - valid
        if unknown:
            raise ValueError(
                f"rule #{i} ({item.get('name', '?')!r}): unknown fields "
                f"{sorted(unknown)}")
        out.append(AlertRule(**item))
    names = [r.name for r in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate rule names: {names}")
    return tuple(out)


def load_rules(path: str) -> Tuple[AlertRule, ...]:
    """Parse an ``--alert-rules`` JSON file."""
    with open(path, "r", encoding="utf-8") as f:
        return parse_rules(json.load(f))


def default_rule_pack(*, fast_s: float = 30.0, slow_s: float = 120.0,
                      for_s: float = 0.0, resolve_s: float = 10.0,
                      shed_limit: float = 0.05, dlq_limit: float = 0.02,
                      p99_ms: float = 2000.0, stall_s: float = 10.0,
                      shadow_disagreement_limit: float = 0.05
                      ) -> Tuple[AlertRule, ...]:
    """The first-party pack over the engine's ``health()`` block — one rule
    per failure mode the codebase models end to end. Paths are
    engine-health-relative; windows/limits parameterize so game days can
    scale them to a scenario's duration (docs/observability.md documents
    each rule's rationale and tuning)."""
    return (
        # Admission control diverting real traffic: shed rows / processed
        # rows burning the availability budget over both windows.
        AlertRule("shed_burn", "burn_rate", num="shed", den="processed",
                  op=">", limit=shed_limit, severity="critical",
                  fast_s=fast_s, slow_s=slow_s, for_s=for_s,
                  resolve_s=resolve_s,
                  description="admission shed rate burning the "
                              "availability budget (docs/scheduling.md)"),
        # The explain breaker opened: the LLM lane is fast-failing.
        AlertRule("breaker_open", "delta", path="breaker.opens", op=">=",
                  limit=1, severity="warning", fast_s=fast_s,
                  slow_s=slow_s, resolve_s=resolve_s,
                  description="explain circuit breaker opened "
                              "(explain/circuit.py)"),
        # Explained-or-accounted coverage of flagged rows dropped below
        # ~1.0: flagged rows are vanishing without even a drop record.
        AlertRule("explain_coverage_drop", "ratio",
                  num="annotations.annotated+annotations.drop_records",
                  den="annotations.submitted", op="<", limit=0.5,
                  severity="critical", for_s=max(for_s, fast_s / 2),
                  resolve_s=resolve_s, min_den=8,
                  fast_s=fast_s, slow_s=slow_s,
                  description="flagged rows neither explained nor "
                              "drop-recorded (docs/explain_serving.md)"),
        # Per-row p99 over the SLO for a sustained window.
        AlertRule("p99_slo_burn", "static", path="row_latency_ms.p99",
                  op=">", limit=p99_ms, severity="warning",
                  for_s=max(for_s, fast_s / 2), resolve_s=resolve_s,
                  fast_s=fast_s, slow_s=slow_s,
                  description="per-row enqueue->produce p99 over the SLO"),
        # Dead-letter rate: malformed/poison rows burning the DLQ budget.
        AlertRule("dlq_rate", "burn_rate", num="dead_lettered",
                  den="processed", op=">", limit=dlq_limit,
                  severity="critical", fast_s=fast_s, slow_s=slow_s,
                  for_s=for_s, resolve_s=resolve_s,
                  description="dead-letter rate over budget "
                              "(docs/robustness.md)"),
        # The engine claims to run but hasn't delivered a batch: a stalled
        # dispatch lane, a wedged device, a dead consumer.
        AlertRule("dispatch_stall", "static", path="last_batch_age_sec",
                  op=">", limit=stall_s, severity="critical",
                  while_path="running", resolve_s=resolve_s,
                  fast_s=fast_s, slow_s=slow_s,
                  description="no delivered batch while running — "
                              "stalled dispatch lane or dead consumer"),
        # Span accounting leak: begun-but-never-ended spans accumulating
        # means some engine path stopped closing its traces.
        AlertRule("spans_leak", "static", path="trace.spans_open", op=">",
                  limit=0, severity="warning",
                  for_s=max(for_s, fast_s / 2), resolve_s=resolve_s,
                  fast_s=fast_s, slow_s=slow_s,
                  description="trace spans_open > 0 sustained "
                              "(obs/trace.py accounting leak)"),
        # Fence/zombie events: commits fenced by rebalances (routine in a
        # rebalancing group, an incident signal for a single static owner).
        AlertRule("fence_events", "delta", path="rebalanced_commits",
                  op=">=", limit=1, severity="warning", fast_s=fast_s,
                  slow_s=slow_s, resolve_s=resolve_s,
                  description="commits fenced by rebalance/zombie fencing "
                              "(docs/fleet.md)"),
        # Shadow disagreement burning: the staged candidate (or, with the
        # learn loop, a drift-corrected retrain) diverges from the primary
        # on RECENT traffic — a two-window burn over the shadow scorer's
        # cumulative disagreement/row counters, so model drift is an
        # INCIDENT even when the learn loop is disabled
        # (docs/online_learning.md; abstains without a shadow block).
        AlertRule("shadow_disagreement_burn", "burn_rate",
                  num="model.shadow.disagreed", den="model.shadow.rows",
                  op=">", limit=shadow_disagreement_limit,
                  severity="warning", fast_s=fast_s, slow_s=slow_s,
                  for_s=for_s, resolve_s=resolve_s, min_den=16,
                  description="shadow candidate disagreement burning over "
                              "recent windows — model drift "
                              "(docs/online_learning.md)"),
        # Restart churn: the supervisor rebuilt the engine twice inside
        # the window — a crash loop, not a one-off blip. Only judgeable
        # through a chain-cumulative source (ChainedHealthSource adds the
        # ``supervisor`` block); inert on a bare engine health.
        AlertRule("restart_churn", "delta", path="supervisor.restarts",
                  op=">=", limit=2, severity="critical", fast_s=fast_s,
                  slow_s=slow_s, resolve_s=resolve_s,
                  description="supervised engine rebuilt repeatedly "
                              "inside the window — crash loop"),
    )


def fleet_rule_pack(*, backlog_limit: float = 5000.0,
                    for_s: float = 0.0, resolve_s: float = 10.0,
                    fast_s: float = 30.0, slow_s: float = 120.0,
                    stale_s: Optional[float] = None,
                    idle_limit: float = 100.0,
                    idle_for_s: Optional[float] = None,
                    flap_limit: float = 3.0
                    ) -> Tuple[AlertRule, ...]:
    """Coordinator-level rules over the aggregated fleet view
    (``FleetCoordinator.tick``'s block under ``"fleet"``) plus the
    per-worker alert states riding the bus.

    ``stale_s`` (default ``fast_s``) is the staleness window for
    ``coordinator_absence`` alone. The two window kinds pull in opposite
    directions: a DELTA rule's window is how long a one-off event (a
    membership drop) stays observable, so wider is safer under sparse
    sampling — but a STALE rule only fires once the counter sat frozen
    for the WHOLE window, so it must stay shorter than the outage it
    exists to catch (an interregnum lasts ~``role_ttl`` plus one
    election; docs/fleet.md "Coordinator succession").

    ``idle_limit``/``idle_for_s`` tune ``fleet_idle`` (the autoscaler's
    scale-IN trigger, docs/autoscaling.md) and ``flap_limit`` tunes
    ``autoscale_flap`` (the control-arm no-flap gate); ``idle_for_s``
    defaults to ``fast_s`` — idleness is only an actionable signal once
    it has been sustained, or every inter-burst lull would shrink the
    fleet."""
    if stale_s is None:
        stale_s = fast_s
    if idle_for_s is None:
        idle_for_s = fast_s
    return (
        # The GLOBAL backlog watermark burning past the shed threshold's
        # neighborhood: the whole fleet is drowning, not one worker.
        AlertRule("fleet_watermark_burn", "static",
                  path="fleet.backlog_per_worker", op=">",
                  limit=backlog_limit, severity="critical", for_s=for_s,
                  resolve_s=resolve_s, fast_s=fast_s, slow_s=slow_s,
                  description="global backlog watermark over the fleet "
                              "shedding threshold (docs/fleet.md)"),
        # Membership dropped inside the window WHILE committed work
        # remains: a worker died or its lease expired mid-stream. The
        # ``while_path`` gate on the fleet's committed lag is what
        # separates a death from a clean drain exit — drain-mode workers
        # leave exactly when the lag clears, and that departure must not
        # read as an incident.
        AlertRule("worker_absence", "delta", path="fleet.n_workers",
                  op="<=", limit=-1, severity="critical",
                  while_path="fleet.committed_lag",
                  fast_s=fast_s, slow_s=slow_s, resolve_s=resolve_s,
                  description="fleet membership shrank while work "
                              "remained — worker death or lease expiry"),
        # Any member's own sentinel is firing: surface it fleet-wide.
        AlertRule("worker_alerts", "static", path="fleet.alerts_firing",
                  op=">=", limit=1, severity="warning",
                  resolve_s=resolve_s, fast_s=fast_s, slow_s=slow_s,
                  description="a worker-level sentinel is firing "
                              "(aggregated from the fleet bus)"),
        # The coordinator's tick counter stopped WHILE committed work
        # remains: the fleet's brain is dead (or partitioned off the
        # control lane) mid-stream. Gated exactly like worker_absence —
        # an interregnum after a clean drain is not an incident. During
        # a real interregnum the succession proxy keeps republishing the
        # dead incumbent's LAST view (fleet/control.py), so the frozen
        # ``fleet.coordinator.ticks`` is precisely the absence signal;
        # the coordinator_kill game day gates detects_within on this.
        AlertRule("coordinator_absence", "stale",
                  path="fleet.coordinator.ticks",
                  while_path="fleet.committed_lag",
                  severity="critical", fast_s=stale_s, slow_s=slow_s,
                  resolve_s=resolve_s,
                  description="coordinator ticks stalled while work "
                              "remained — coordinator death or control-"
                              "lane partition (docs/fleet.md)"),
        # Sustained LOW backlog per live member: spare capacity the
        # autoscaler can return (fleet/autoscale/ scale-in trigger).
        # Double-guarded against the empty-topic trap: ``min_den=1``
        # abstains when the view shows no members (an interregnum's 0/0
        # must not read as idle), and ``while_path`` on the fleet's
        # cumulative processed counter abstains until traffic has
        # actually flowed — a fleet that never saw a row is WAITING,
        # not idle, and must not shrink→flap on startup.
        AlertRule("fleet_idle", "ratio", num="fleet.global_backlog",
                  den="fleet.n_workers", op="<", limit=idle_limit,
                  severity="warning", min_den=1,
                  while_path="fleet.processed_total",
                  for_s=idle_for_s, resolve_s=resolve_s,
                  fast_s=fast_s, slow_s=slow_s,
                  description="sustained low backlog per worker after "
                              "traffic flowed — spare capacity "
                              "(docs/autoscaling.md)"),
        # The fleet resized ``flap_limit`` times inside the window: the
        # policy is oscillating (hysteresis/cooldown mistuned), not
        # tracking load. Sums the CUMULATIVE scale counters, so the rule
        # abstains entirely while the autoscale block is absent (a
        # static fleet can never flap).
        AlertRule("autoscale_flap", "delta",
                  path="fleet.autoscale.scale_outs"
                       "+fleet.autoscale.scale_ins"
                       "+fleet.autoscale.replacements",
                  op=">=", limit=flap_limit, severity="warning",
                  fast_s=slow_s, slow_s=slow_s, resolve_s=resolve_s,
                  description="repeated scale events inside the window — "
                              "autoscale oscillation "
                              "(docs/autoscaling.md)"),
        # The role changed hands twice inside the window: an election
        # storm (flapping incumbents, a term war), not a one-off
        # failover — one clean succession must NOT fire this.
        AlertRule("failover_churn", "delta",
                  path="fleet.coordinator.handoffs", op=">=", limit=2,
                  severity="warning", fast_s=fast_s, slow_s=slow_s,
                  resolve_s=resolve_s,
                  description="repeated coordinator handoffs inside the "
                              "window — election churn (docs/fleet.md)"),
    )
