"""Tracecraft: end-to-end row/batch tracing for the serving pipeline.

The pipeline grew deep — admission -> batch formation -> lane ``_prepare``/
``_launch`` -> device dispatch -> delivery -> async annotation -> DLQ —
and its only windows were per-component ``health()`` aggregates: when the
headline moves, nothing says WHICH stage, which worker, which rung. This
module adds the missing attribution layer, Dapper-style but sized for a
50k rows/sec hot loop:

* A **correlation id is minted per polled batch** (``<worker>-<seq>``) and
  every row derives a stable id from it (``<batch>:<partition>:<offset>``)
  — the same coordinates DLQ/shed records already carry, so a dead-lettered
  row joins back to its spans by construction.
* **Spans are batch-granular** ("poll", "admit", "launch", "device",
  "deliver") with **row-granular events** for the interesting minority
  (shed, dlq, flag, annotate): per-row spans for every clean row would cost
  more than the work they measure; per-batch spans plus row events keep the
  overhead under the bench's 5%% tracing budget while still giving every
  flagged/shed/DLQ'd row a complete poll->terminal chain by id.
* Spans buffer **batch-locally** (no shared state while the batch is in
  flight) and commit into a fixed-size ring in ONE append per batch at the
  terminal (deliver/abort). The ring drops OLDEST on overflow and counts
  the drop — it never blocks the hot path, and the counter makes the loss
  an explicit recorded fact.
* **Head sampling with forced keeps**: each batch draws its keep/discard
  fate at mint time (seeded RNG, ``sample`` fraction), but a batch that
  turns out interesting — flagged, shed, dead-lettered, breaker-tripped,
  aborted — is kept REGARDLESS of the draw. Sampling controls the clean-
  traffic volume; accountability rows are always-on.
* **Exact accounting**: every span begun is ended (context managers +
  explicit abort on the engine's failure paths), and ``begun == ended`` is
  a pinned invariant under seeded chaos and fleet worker kills
  (tests/test_obs.py).
* Per-stage wall time also feeds one :class:`LatencySketch` per stage
  (bounded memory, lossless merge), independent of sampling — the fleet
  aggregation and the bench's ``stages`` attribution block read these, so
  p50/p99 per stage covers ALL batches, not the sampled subset.

Thread model: a batch's trace is owned by whichever thread is driving that
batch leg (engine driver, dispatch lane, annotation lane) — legs hand off
strictly FIFO, never concurrently. Tracer-global state (the ring, the
counters, the stage sketches) is guarded by one small lock held O(1) per
BATCH, not per row or per span.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

from fraud_detection_tpu.sched.sketch import LatencySketch

# The span vocabulary (docs/observability.md). Batch stages carry
# durations; row events are instantaneous markers with a reason.
STAGE_POLL = "poll"          # batch minted from a poll (rows, wait)
STAGE_ADMIT = "admit"        # admission + poison screen (driver)
STAGE_LAUNCH = "launch"      # featurize + upload + device launch
STAGE_DEVICE = "device"      # blocking on device results
STAGE_DELIVER = "deliver"    # produce + flush + commit
STAGE_EXPLAIN = "explain"    # one LLM explain call (annotation lane)
EVENT_SHED = "shed"          # row diverted by admission control
EVENT_DLQ = "dlq"            # row dead-lettered (malformed/poison)
EVENT_FLAG = "flag"          # row classified non-benign
EVENT_ANNOTATE = "annotate"  # row's annotation produced (or failed)
EVENT_ABORT = "abort"        # batch abandoned (crash/flush-fail replay)
EVENT_ROW = "row"            # row delivered (record mode only: the full
                             # per-batch row census a trace RECORDING needs
                             # for exact replay — scenarios/record.py)


class Span(NamedTuple):
    """One recorded span/event. ``cid`` is the batch correlation id for
    batch stages and the row id (``<batch>:<part>:<off>``) for row
    events; ``detail`` is a small JSON-safe annotation (row counts,
    shed/DLQ reason, ...). A NamedTuple, not a dataclass: row events are
    created per flagged/shed row on the hot path and construction cost is
    the tracing overhead budget's biggest line item."""

    cid: str
    stage: str
    start: float            # wall-clock seconds (time.time domain)
    duration_ms: float
    ok: bool = True
    detail: Optional[str] = None

    def as_dict(self) -> dict:
        return {"cid": self.cid, "stage": self.stage,
                "start": round(self.start, 6),
                "duration_ms": round(self.duration_ms, 4),
                "ok": self.ok, "detail": self.detail}


class _RowEvents(NamedTuple):
    """A batch of row events stored COMPACT: one ring entry carrying the
    rows' (partition, offset) int pairs instead of N materialized Spans —
    at a 50% flag rate the hot path would otherwise build ~2000 Span
    objects + cid strings per micro-batch, which alone blows the 5%
    tracing-overhead budget. Expansion to Spans (cid strings included)
    happens at read time (snapshot/chain), where nobody is counting
    microseconds."""

    prefix: str             # batch correlation id
    stage: str
    pairs: tuple            # ((partition, offset), ...)
    start: float
    ok: bool = True
    detail: Optional[str] = None

    def expand(self) -> List[Span]:
        return [Span(f"{self.prefix}:{p}:{o}", self.stage, self.start,
                     0.0, self.ok, self.detail) for p, o in self.pairs]


def _weight(entry) -> int:
    return len(entry.pairs) if type(entry) is _RowEvents else 1


class SpanRing:
    """Fixed-capacity span store: drop-OLDEST on overflow, drops counted,
    O(1) per append with one small lock — appends never wait on readers
    (snapshot copies under the same lock and returns). Entries are Spans
    or compact :class:`_RowEvents` blocks; capacity, depth, and the
    recorded/dropped counters all count SPANS (a dropped block counts
    every row event it carried — overflow honesty is span-granular)."""

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity            # entries held at most
        self._buf: List[Optional[object]] = [None] * capacity
        self._next = 0          # next write slot
        self._len = 0           # entries held
        self._spans = 0         # span-weight currently held
        self.recorded = 0       # spans ever appended
        self.dropped = 0        # spans overwritten before anyone read them
        self._lock = threading.Lock()

    def extend(self, entries: Sequence[object]) -> None:
        n = 0
        with self._lock:
            for e in entries:
                w = _weight(e)
                n += w
                if self._len == self.capacity:
                    old = self._buf[self._next]
                    ow = _weight(old)
                    self.dropped += ow
                    self._spans -= ow
                else:
                    self._len += 1
                self._buf[self._next] = e
                self._spans += w
                self._next = (self._next + 1) % self.capacity
            self.recorded += n

    def __len__(self) -> int:
        """Spans currently held (expanded count, not entries)."""
        with self._lock:
            return self._spans

    def snapshot(self) -> List[Span]:
        """Oldest -> newest expanded copy of the live spans."""
        with self._lock:
            if self._len < self.capacity:
                entries = self._buf[: self._len]
            else:
                entries = self._buf[self._next:] + self._buf[: self._next]
        out: List[Span] = []
        for e in entries:
            if type(e) is _RowEvents:
                out.extend(e.expand())
            else:
                out.append(e)
        return out


class BatchTrace:
    """One polled batch's trace context: batch-local span buffer plus the
    keep/sample fate. NOT thread-safe on its own — a batch leg is owned by
    exactly one thread at a time (driver -> lane -> driver, strict FIFO),
    which is the engine's existing handoff contract."""

    __slots__ = ("tracer", "cid", "sampled", "keep", "spans", "committed")

    def __init__(self, tracer: "RowTracer", cid: str, sampled: bool):
        self.tracer = tracer
        self.cid = cid
        self.sampled = sampled
        self.keep = False           # forced keep: flagged/shed/dlq/abort
        self.spans: List[Span] = []
        self.committed = False

    # -- batch stages ---------------------------------------------------

    def span(self, stage: str, *, detail: Optional[str] = None):
        """Context manager timing one batch stage; exception-safe (the
        span ends, ok=False, and re-raises)."""
        return _SpanCtx(self, stage, detail)

    def add(self, stage: str, duration_sec: float, *, ok: bool = True,
            detail: Optional[str] = None,
            start: Optional[float] = None) -> None:
        """Record an already-measured batch stage (the engine's existing
        ``dispatch_time`` style timings)."""
        t = self.tracer
        t._count_begin_end()
        self.spans.append(Span(self.cid, stage,
                               t._wall() if start is None else start,
                               duration_sec * 1e3, ok, detail))
        t._observe_stage(stage, duration_sec)

    # -- row events -----------------------------------------------------

    def row_cid(self, msg) -> str:
        """The stable per-row correlation id: batch cid + the row's source
        coordinates (the same (partition, offset) its DLQ record carries)."""
        return f"{self.cid}:{msg.partition}:{msg.offset}"

    def event(self, stage: str, cid: str, *, ok: bool = True,
              detail: Optional[str] = None) -> None:
        """Instantaneous row-level marker; marks the batch kept (row
        events only exist for interesting rows)."""
        t = self.tracer
        t._count_begin_end()
        self.keep = True
        self.spans.append(Span(cid, stage, t._wall(), 0.0, ok, detail))

    def events_rows(self, stage: str, pairs: List[tuple], *,
                    ok: bool = True, detail: Optional[str] = None) -> None:
        """Batched row markers stored COMPACT (``pairs`` = the rows'
        (partition, offset) coordinates): one counter bump, one wall
        read, ONE ring entry for the whole list. This is the
        per-flagged-row path at 50k rows/sec — the tracing overhead
        budget lives or dies here; Span objects and cid strings only
        materialize when somebody reads the ring."""
        if not pairs:
            return
        t = self.tracer
        t._count(len(pairs))
        self.keep = True
        self.spans.append(_RowEvents(self.cid, stage, tuple(pairs),
                                     t._wall(), ok, detail))

    def shed(self, msg, reason: str) -> str:
        """Row diverted by admission control; returns the row cid so the
        DLQ record can carry it."""
        cid = self.row_cid(msg)
        self.event(EVENT_SHED, cid, ok=False, detail=reason)
        return cid

    def dlq(self, msg, reason: str) -> str:
        """Row dead-lettered (malformed / poison); returns the row cid."""
        cid = self.row_cid(msg)
        self.event(EVENT_DLQ, cid, ok=False, detail=reason)
        return cid


class _SpanCtx:
    __slots__ = ("bt", "stage", "detail", "_t0", "_w0")

    def __init__(self, bt: BatchTrace, stage: str, detail: Optional[str]):
        self.bt = bt
        self.stage = stage
        self.detail = detail

    def __enter__(self):
        self._w0 = self.bt.tracer._wall()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        bt, t = self.bt, self.bt.tracer
        t._count_begin_end()
        bt.spans.append(Span(bt.cid, self.stage, self._w0, dt * 1e3,
                             exc_type is None, self.detail))
        t._observe_stage(self.stage, dt)
        return False


class RowTracer:
    """Per-worker tracing context (see module docstring). One per engine/
    fleet worker; shared across supervised incarnations so chains survive
    restarts exactly like the DLQ poison tracker does."""

    def __init__(self, *, worker: str = "w0", capacity: int = 4096,
                 sample: float = 1.0, seed: Optional[int] = None,
                 record_rows: bool = False,
                 wall: Callable[[], float] = time.time):
        if not 0.0 <= sample <= 1.0:
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        if record_rows and sample < 1.0:
            # A recording exists to replay the run's EXACT row set;
            # head-sampling away clean batches would silently hole it.
            raise ValueError(
                f"record_rows needs sample=1.0 (got {sample}): a sampled "
                "recording cannot reproduce the run's row set")
        self.worker = worker
        self.sample = sample
        # Record mode (scenarios/record.py): the engine adds one compact
        # EVENT_ROW block per delivered batch carrying EVERY row's source
        # coordinates — the census a recorded trace needs for exact
        # replay. Off (the default), clean rows stay un-enumerated and
        # only the interesting minority gets row events.
        self.record_rows = bool(record_rows)
        self.ring = SpanRing(capacity)
        self._rng = random.Random(seed)
        self._wall = wall
        self._lock = threading.Lock()
        self._seq = 0
        # Exact span accounting: every begin is matched by an end (spans
        # are only ever created fully-formed, so the pair increments land
        # together — the invariant the chaos tests pin is that no path
        # creates a begun-but-never-ended span, i.e. open == 0 at rest).
        self.spans_begun = 0
        self.spans_ended = 0
        self.batches_traced = 0     # batch traces minted
        self.batches_closed = 0     # committed or aborted
        self.kept = 0               # batches whose spans entered the ring
        self.sampled_out = 0        # clean batches discarded by sampling
        self._stages: Dict[str, LatencySketch] = {}

    # -- internal hooks (BatchTrace) ------------------------------------

    def _count_begin_end(self) -> None:
        self._count(1)

    def _count(self, n: int) -> None:
        with self._lock:
            self.spans_begun += n
            self.spans_ended += n

    def _observe_stage(self, stage: str, duration_sec: float) -> None:
        sk = self._stages.get(stage)
        if sk is None:
            with self._lock:
                sk = self._stages.setdefault(stage, LatencySketch())
        sk.add(duration_sec)

    # -- engine surface -------------------------------------------------

    def batch_begin(self, n_rows: int, *,
                    poll_wait_sec: float = 0.0) -> BatchTrace:
        """Mint a batch correlation id + its trace context at poll time.
        The head-sampling draw happens HERE; interesting outcomes flip the
        batch to kept later (forced keeps are outcome-driven, the draw
        only throttles clean traffic)."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            self.batches_traced += 1
            sampled = self._rng.random() < self.sample
        bt = BatchTrace(self, f"{self.worker}-{seq:x}", sampled)
        bt.add(STAGE_POLL, poll_wait_sec, detail=f"rows={n_rows}")
        return bt

    def commit(self, bt: Optional[BatchTrace]) -> None:
        """Terminal for a delivered batch: push its spans into the ring
        when kept (sampled or forced), count it out otherwise. Idempotent
        — abort-then-commit races on engine unwind paths count once."""
        if bt is None or bt.committed:
            return
        bt.committed = True
        with self._lock:
            self.batches_closed += 1
            if bt.keep or bt.sampled:
                self.kept += 1
            else:
                self.sampled_out += 1
                return
        self.ring.extend(bt.spans)

    def abort(self, bt: Optional[BatchTrace], reason: str = "abort") -> None:
        """Terminal for an abandoned batch (crash / flush failure / replay
        discard): always kept — an aborted batch is interesting by
        definition."""
        if bt is None or bt.committed:
            return
        bt.event(EVENT_ABORT, bt.cid, ok=False, detail=reason)
        self.commit(bt)

    # -- direct records (post-terminal legs: annotation lane) ------------

    def record_span(self, cid: str, stage: str, duration_sec: float, *,
                    ok: bool = True, detail: Optional[str] = None) -> None:
        """Record a span straight into the ring — for legs that run AFTER
        a batch's terminal commit (the annotation lane's explain calls).
        Only call for rows/legs that are always-kept (flagged rows are);
        head sampling does not apply here."""
        self._count_begin_end()
        self.ring.extend([Span(cid, stage, self._wall(),
                               duration_sec * 1e3, ok, detail)])
        self._observe_stage(stage, duration_sec)

    def record_event(self, cid: str, stage: str, *, ok: bool = True,
                     detail: Optional[str] = None) -> None:
        """Instantaneous direct marker (see :meth:`record_span`)."""
        self._count_begin_end()
        self.ring.extend([Span(cid, stage, self._wall(), 0.0, ok, detail)])

    # -- retrieval + export (any thread) --------------------------------

    def chain(self, cid: str) -> List[Span]:
        """Every recorded span on a correlation id's chain, oldest first.
        A ROW cid (``<batch>:<part>:<off>``) pulls its batch's stage spans
        plus the row's own events; a batch cid pulls the batch spans and
        all its rows' events."""
        batch_cid = cid.split(":", 1)[0]
        out = []
        for s in self.ring.snapshot():
            if s.cid == cid or s.cid == batch_cid or (
                    cid == batch_cid and s.cid.split(":", 1)[0] == batch_cid):
                out.append(s)
        return out

    def stage_quantiles(self) -> Dict[str, dict]:
        """Per-stage latency snapshot (ms quantiles + counts) over ALL
        batches — sampling-independent; the bench ``stages`` block and
        the fleet aggregation read this."""
        with self._lock:
            stages = dict(self._stages)
        return {name: sk.snapshot() for name, sk in sorted(stages.items())}

    def stages_wire(self) -> Dict[str, dict]:
        """Per-stage sketches in wire form (lossless bucket counts) for
        the fleet bus — the coordinator merges these exactly."""
        with self._lock:
            stages = dict(self._stages)
        return {name: sk.to_wire() for name, sk in sorted(stages.items())}

    def snapshot(self) -> dict:
        """The ``trace`` block of ``health()`` (schema pinned in
        tests/test_obs.py TRACE_BLOCK_SCHEMA, FC301-checked)."""
        with self._lock:
            begun, ended = self.spans_begun, self.spans_ended
            traced, closed = self.batches_traced, self.batches_closed
            kept, sampled_out = self.kept, self.sampled_out
        return {
            "worker": self.worker,
            "sample": self.sample,
            "spans_begun": begun,
            "spans_ended": ended,
            "spans_open": begun - ended,
            "batches_traced": traced,
            "batches_closed": closed,
            "kept": kept,
            "sampled_out": sampled_out,
            "ring_depth": len(self.ring),
            "ring_capacity": self.ring.capacity,
            "ring_recorded": self.ring.recorded,
            "ring_dropped": self.ring.dropped,
            "stages": self.stage_quantiles(),
        }


def aggregate_stage_wires(wires: Sequence[Dict[str, dict]]
                          ) -> Dict[str, LatencySketch]:
    """Merge per-worker stage-sketch wires into one sketch per stage —
    LOSSLESS (bucket counts add), so fleet-level p50/p99 per stage equals
    a single-process run over the same samples (pinned in
    tests/test_obs.py)."""
    merged: Dict[str, LatencySketch] = {}
    for wire in wires:
        if not isinstance(wire, dict):
            continue
        for stage, w in wire.items():
            sk = LatencySketch.from_wire(w)
            if sk is None:
                continue
            into = merged.get(stage)
            if into is None:
                merged[stage] = sk
            else:
                into.merge(sk)
    return merged


def fleet_stage_latency(wires: Sequence[Dict[str, dict]]) -> Dict[str, dict]:
    """The fleet view's ``stage_latency_ms`` block: merged per-stage
    quantile snapshots across every worker's published wire."""
    return {stage: sk.snapshot()
            for stage, sk in sorted(aggregate_stage_wires(wires).items())}
