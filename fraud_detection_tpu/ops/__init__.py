"""Pallas TPU kernels for the framework's hot ops.

Histogram tree building reformulated as MXU matmuls (ops/histogram.py) —
the kernels BASELINE.json calls for. XLA fallback paths live next to every
kernel; off-TPU the kernels run in interpreter mode so the CPU test mesh
exercises them.
"""

from fraud_detection_tpu.ops.histogram import (
    auto_interpret,
    best_splits,
    histogram_reference,
    node_feature_bin_histogram,
    node_feature_bin_histogram_multi,
)

__all__ = [
    "auto_interpret",
    "best_splits",
    "histogram_reference",
    "node_feature_bin_histogram",
    "node_feature_bin_histogram_multi",
]
