"""Pallas TPU kernels for the framework's hot ops.

Histogram tree building reformulated as MXU matmuls (ops/histogram.py) —
the kernels BASELINE.json calls for — and device-side featurization
(ops/featurize_kernel.py): a byte-scan kernel that moves the serving
path's tokenize/murmur-hash/TF-count leg off the host entirely. XLA
fallback paths live next to every kernel; off-TPU the kernels run in
interpreter mode so the CPU test mesh exercises them.
"""

from fraud_detection_tpu.ops.featurize_kernel import (
    FeaturizeSpec,
    build_stop_table,
    featurize_bytes,
    featurize_bytes_jit,
)
from fraud_detection_tpu.ops.histogram import (
    auto_interpret,
    best_splits,
    histogram_reference,
    node_feature_bin_histogram,
    node_feature_bin_histogram_multi,
)

__all__ = [
    "FeaturizeSpec",
    "auto_interpret",
    "best_splits",
    "build_stop_table",
    "featurize_bytes",
    "featurize_bytes_jit",
    "histogram_reference",
    "node_feature_bin_histogram",
    "node_feature_bin_histogram_multi",
]
