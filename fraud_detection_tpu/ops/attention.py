"""Pallas TPU flash attention for the on-pod LLM's single-chip path.

``models/llm.py _attend`` materializes the full (B, H, T, S) score matrix —
fine for short prompts, O(T^2) memory for long transcripts (the workload
SURVEY.md §5 long-context calls out). This kernel is the standard
flash-attention reformulation on TPU: block over (query, key) tiles, keep a
running row max / normalizer / output accumulator in VMEM scratch, and never
materialize scores — memory O(T * d) while both matmuls (q·k^T and p·v) run
on the MXU. The cross-chip analogue (sequence-parallel ring attention,
``models/llm.py ring_attention``) uses the same online-softmax algebra with
K/V blocks arriving over ICI instead of from HBM.

Causal-only by design: the decoder has no non-causal path, and causality is
what lets sequence padding ride for free (padded key columns sit above the
diagonal for every real query row, so the mask discards them).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fraud_detection_tpu.ops.histogram import _round_up, auto_interpret  # noqa: F401

_NEG = -1e30  # mask value: exp(s - m) underflows to exactly 0, no inf-inf NaNs


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, blk_q: int, blk_k: int, n_k: int):
    """One (batch*head, q-block, k-block) cell. The grid runs k innermost, so
    the scratch accumulators carry across k blocks of one q block; the causal
    gate skips cells entirely above the diagonal (their K/V blocks still DMA,
    but the matmuls — the dominant cost — are skipped)."""
    qi = pl.program_id(1)
    si = pl.program_id(2)

    @pl.when(si == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(si * blk_k <= qi * blk_q + (blk_q - 1))
    def _block():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale        # (blk_q, blk_k)
        rows = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = si * blk_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(rows >= cols, s, _NEG)

        m_prev = m_ref[:, 0:1]                                 # (blk_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                                 # masked -> 0
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_ref[:, 0:1] + jnp.sum(p, axis=1, keepdims=True),
            l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(si == n_k - 1)
    def _emit():
        o_ref[0] = (acc_ref[:] / l_ref[:, 0:1]).astype(o_ref.dtype)


@partial(jax.jit, static_argnames=("blk_q", "blk_k", "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    blk_q: int = 0, blk_k: int = 0,
                    interpret: bool = False) -> jax.Array:
    """Causal flash attention. q: (B, T, H, d); k/v: (B, T, Hkv, d) with
    H % Hkv == 0 — GQA/MQA kv stay at their NATIVE width and the kernel's
    index map hands each query head its group's K/V block, so nothing
    expands: on Gemma-2B (MQA, H=8, Hkv=1) the pre-r5 caller-side
    ``jnp.repeat`` materialized and streamed 8x the K/V bytes. Hkv == H
    recovers plain MHA. Returns (B, T, H, d). Matches
    ``_attend(q, expand(k), expand(v), tril)`` to f32 round-off; enforced
    by tests/test_flash_attention.py.

    ``blk_q``/``blk_k`` default (0) to shape-aware auto-selection: 512x512
    for T >= 512, else 128x128. Each query block re-streams ALL of K/V
    through VMEM, so K/V DMA scales as (T/blk_q)*T — on the 2B serving
    config the 128x128 default measured 16.0k prefill tok/s at T=8192
    (45.6% MFU) vs 26.8-27.5k at 512-wide blocks (76-78% MFU), with
    T=2048 improving 22.9k -> 27.9k too (device sweep, r5). 512x512 keeps
    the f32 score tile + accumulators comfortably inside VMEM (~3MB).
    Ragged T guard: wide blocks also widen t_pad, and padded q-blocks run
    both matmuls before being sliced off — so auto-selection takes the
    largest block adding at most ~12.5% padding over the 128-granularity
    floor (T=4000 -> 512 via 1.6% waste; T=640 stays 128, where 512
    would pad 60%)."""
    B, T, H, d = q.shape
    h_kv = k.shape[2]
    if H % h_kv or v.shape[2] != h_kv:
        raise ValueError(f"kv heads {k.shape[2]}/{v.shape[2]} must divide "
                         f"query heads {H}")
    rep = H // h_kv
    if not blk_q or not blk_k:
        floor = _round_up(T, 128)
        auto = next(b for b in (512, 256, 128)
                    if _round_up(T, b) * 8 <= floor * 9)
        blk_q = blk_q or auto
        blk_k = blk_k or auto
    scale = 1.0 / math.sqrt(d)
    d_pad = _round_up(d, 128)
    t_pad = _round_up(T, max(blk_q, blk_k))

    def prep(x):
        h = x.shape[2]
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(B * h, T, d)
        return jnp.pad(x, ((0, 0), (0, t_pad - T), (0, d_pad - d)))

    qf, kf, vf = prep(q), prep(k), prep(v)
    n_q, n_k = t_pad // blk_q, t_pad // blk_k

    def kv_row(b, qi, si):
        # grid row b = bi * H + hi over (B*H); its kv row is
        # bi * Hkv + hi // rep over (B*Hkv).
        return (b // H) * h_kv + (b % H) // rep, si, 0

    out = pl.pallas_call(
        partial(_flash_kernel, scale=scale, blk_q=blk_q, blk_k=blk_k, n_k=n_k),
        grid=(B * H, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, d_pad), lambda b, qi, si: (b, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, d_pad), kv_row,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, d_pad), kv_row,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, blk_q, d_pad), lambda b, qi, si: (b, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * H, t_pad, d_pad), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, 128), jnp.float32),   # running row max
            pltpu.VMEM((blk_q, 128), jnp.float32),   # running normalizer
            pltpu.VMEM((blk_q, d_pad), jnp.float32), # output accumulator
        ],
        interpret=interpret,
    )(qf, kf, vf)

    out = out[:, :T, :d].reshape(B, H, T, d)
    return jnp.transpose(out, (0, 2, 1, 3))
