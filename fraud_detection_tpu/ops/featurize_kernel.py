"""Pallas TPU kernel for device-side featurization: raw UTF-8 bytes in,
packed (B, 2, L) ids/counts staging layout out.

The serving hot path's last host-side compute is the featurize leg —
clean/tokenize/murmur-hash/count (featurize/text.py + featurize/hashing.py,
~130–200k rows/sec of host CPU at bench scale against a device ladder with
far more capacity). This module moves that leg on-device: the host ships a
fixed-width ``(B, W)`` uint8 byte tensor (a straight memcpy of each
dialogue's UTF-8 bytes — no tokenization, no hashing, no regex on host) and
ONE jitted device program reproduces the exact Spark-parity pipeline:

  * **clean_text** — lowercase + strip every char not in ``[a-z ]``. Byte
    classing is embarrassingly parallel XLA (``byte_classes``). Exactly two
    codepoints outside ASCII lowercase into ``[a-z ]`` under Python's
    ``str.lower`` (U+0130 → 'i', U+212A → 'k' — re-derived over all of
    Unicode by tests/test_featurize_device.py), so multi-byte sequences
    reduce to two pattern matches; every other non-ASCII byte strips, which
    is byte-for-byte what the host regex does after ``.lower()``.
  * **tokenize** — Spark ``Tokenizer``/Java ``split("\\s")`` semantics
    (interior/leading empty tokens kept, trailing dropped, ``"" → [""]``).
    Runs in the Pallas scan kernel: one pass over byte positions, rows
    vectorized across the VPU, emitting a finalized token at every
    field boundary.
  * **murmur3_x86_32** — exact ``spark_hash_bucket`` semantics including
    the legacy sign-extended-tail variant, streamed byte-by-byte through
    the same scan (state: h1, pending tail word, byte count).
  * **stop words** — exact membership against the featurizer's stop list.
    Cleaned tokens are ``[a-z]*``, so a token of ≤ ``_STOP_PACK_CHARS``
    chars is IDENTIFIED by its packed 5-bit char words + length; the scan
    emits those alongside the hash and the XLA post-pass probes a
    direct-mapped table (``build_stop_table``, collision-free by
    construction). Stop words that cannot match any cleaned token (non
    ``[a-z]`` chars) are dropped from the table host-side; a pure-alpha
    stop word longer than the pack width makes the device path refuse
    (honest fallback) rather than silently diverge.
  * **count + pack** — bucket = nonNegativeMod(signed hash, F), per-row
    unique-bucket counting via sort + segment-sum, host truncation rule
    (keep top counts, ties toward the LOWER bucket id) when a row has more
    unique buckets than ``n_slots``, then the same packed ``(B, 2, L)``
    int16 staging layout ``models/pipeline._pack_encoded`` produces — so
    every downstream scoring path (fused LR, int8, trees) is unchanged.

IDF scaling already lives on device (folded into LR weights /
``idf_array`` for trees), so with this kernel the packed staging buffer —
and upstream of it, the raw byte tensor — is the only host artifact on the
scoring path.

Like ``ops/histogram.py``, the kernel runs under ``interpret=True``
off-TPU so the CPU test mesh pins parity; ``interpreter_can_run()`` is the
environment-only capability canary (PR 9 style) the tests and the serving
probe share.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401 — VMEM specs

from fraud_detection_tpu.featurize.hashing import SPARK_HASHING_TF_SEED

# Character classes produced by byte_classes: 1..26 = 'a'..'z', the rest
# as named below. Everything stripped by clean_text is NOP.
CLS_NOP = 0
CLS_SPACE = 27
CLS_END = 28

#: The only codepoints whose ``str.lower()`` contains chars in ``[a-z ]``
#: (pinned by an exhaustive re-derivation in tests/test_featurize_device.py).
#: İ (U+0130) lowercases to "i" + combining dot — the 'i' survives the
#: strip; K (U+212A, Kelvin) lowercases to 'k'. Their UTF-8 encodings.
SPECIAL_LOWER = ((b"\xc4\xb0", ord("i")), (b"\xe2\x84\xaa", ord("k")))

# Stop-word identity pack: cleaned tokens are [a-z]*, so 5 bits/char and
# two 30-bit words identify any token up to 12 chars exactly (length is
# compared too). The longest word in Spark's default English list is 10.
_STOP_PACK_CHARS = 12
_STOP_TABLE_MAX = 1 << 16

ROW_TILE = 128

_MASK32 = 0xFFFFFFFF


class FeaturizeSpec(NamedTuple):
    """Static (hashable) configuration of the device featurize program —
    everything that changes the compiled kernel, as jit static args."""

    num_features: int
    n_slots: int            # token slots L in the packed output
    binary: bool            # HashingTF(binary=True): presence, not counts
    legacy: bool            # murmur legacy sign-extended-tail variant
    empty_bucket: int       # spark_hash_bucket("") — the "" token's bucket
    empty_is_stop: bool     # "" present in the stop list
    row_tile: int = ROW_TILE
    interpret: bool = False


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# ---------------------------------------------------------------------------
# murmur3 x86_32 primitives (uint32 vector ops — usable inside the kernel)
# ---------------------------------------------------------------------------

def _mix_k1(k1):
    # Constants are built at trace time INSIDE the kernel: Pallas refuses
    # closure-captured device arrays (jax 0.4.x), inline scalars are fine.
    k1 = k1 * jnp.uint32(0xCC9E2D51)
    k1 = (k1 << 15) | (k1 >> 17)
    return k1 * jnp.uint32(0x1B873593)


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = (h1 << 13) | (h1 >> 19)
    return h1 * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h1, length_u32):
    h1 = h1 ^ length_u32
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * jnp.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * jnp.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


# ---------------------------------------------------------------------------
# clean_text as byte classing (XLA, embarrassingly parallel)
# ---------------------------------------------------------------------------

def byte_classes(byts: jax.Array, lengths: jax.Array) -> jax.Array:
    """(B, W) uint8 + (B,) lengths -> (B, W+1) int32 char classes.

    Implements clean_text byte-exactly: ASCII A-Z lowercases, a-z and space
    keep, everything else strips — except the two SPECIAL_LOWER sequences,
    whose lead byte emits the surviving ASCII letter (their continuation
    bytes are >= 0x80 and strip like any other). Position ``lengths[r]``
    carries CLS_END (the scan's flush trigger); the column always exists
    because the class tensor is one wider than the byte tensor.
    """
    b = byts.astype(jnp.int32)
    nxt1 = jnp.pad(b[:, 1:], ((0, 0), (0, 1)))
    nxt2 = jnp.pad(b[:, 2:], ((0, 0), (0, 2)))
    upper = (b >= 65) & (b <= 90)
    lower = (b >= 97) & (b <= 122)
    cls = jnp.where(upper, b - 64, jnp.where(lower, b - 96, CLS_NOP))
    cls = jnp.where(b == 32, CLS_SPACE, cls)
    (s_i, ch_i), (s_k, ch_k) = SPECIAL_LOWER
    cls = jnp.where((b == s_i[0]) & (nxt1 == s_i[1]), ch_i - 96, cls)
    cls = jnp.where((b == s_k[0]) & (nxt1 == s_k[1]) & (nxt2 == s_k[2]),
                    ch_k - 96, cls)
    cls = jnp.pad(cls, ((0, 0), (0, 1)))
    pos = jnp.arange(cls.shape[1], dtype=jnp.int32)[None, :]
    ln = lengths.astype(jnp.int32)[:, None]
    return jnp.where(pos < ln, cls, jnp.where(pos == ln, CLS_END, CLS_NOP))


# ---------------------------------------------------------------------------
# the scan kernel: tokenize + murmur + stop-key pack, one pass over bytes
# ---------------------------------------------------------------------------

def _scan_kernel(cls_ref, h_ref, w0_ref, w1_ref, tl_ref, emp_ref, *,
                 legacy: bool):
    """One row tile: sequential scan over byte positions, rows vectorized.

    Per step, every row advances its token state by one char class: letters
    stream into the murmur word accumulator and the 5-bit identity pack;
    a space or the end flush the current field. Emissions land at the
    CURRENT column (each position closes at most one field), so the output
    streams are (R, W+1) with no data-dependent scatter: ``tl`` >= 0 marks
    a real token (its byte length), -1 an empty slot.

    Java-split semantics ride two per-row counters: ``pend`` accumulates
    empty fields whose interior-ness is unknown until a later non-empty
    field confirms it (trailing empties die in ``pend``), and ``emp`` is
    the confirmed empty-token count — plus the ``"" -> [""]`` rule when the
    cleaned row kept no chars at all.
    """
    nrows, ncols = cls_ref.shape
    seed_v = jnp.full((nrows, 1), SPARK_HASHING_TF_SEED, jnp.uint32)
    zero_u = jnp.zeros((nrows, 1), jnp.uint32)
    zero_i = jnp.zeros((nrows, 1), jnp.int32)

    def step(j, st):
        h1, k1, nb, w0, w1, pend, emp, kept = st
        c = cls_ref[:, pl.dslice(j, 1)]
        is_let = (c >= 1) & (c <= 26)
        is_space = c == CLS_SPACE
        is_end = c == CLS_END

        # letter: stream the byte into murmur (body words complete every
        # 4th byte) and the identity pack (first _STOP_PACK_CHARS chars).
        vb = jnp.where(is_let, c + 96, 0).astype(jnp.uint32)
        k1n = jnp.where(is_let, k1 | (vb << ((nb & 3) * 8).astype(jnp.uint32)),
                        k1)
        word_full = is_let & ((nb & 3) == 3)
        h1n = jnp.where(word_full, _mix_h1(h1, _mix_k1(k1n)), h1)
        k1n = jnp.where(word_full, zero_u, k1n)
        cw = jnp.where(is_let, c, 0)
        w0n = jnp.where(is_let & (nb < 6),
                        w0 | (cw << (5 * jnp.minimum(nb, 6))), w0)
        w1n = jnp.where(is_let & (nb >= 6) & (nb < _STOP_PACK_CHARS),
                        w1 | (cw << (5 * jnp.clip(nb - 6, 0, 6))), w1)
        nbn = jnp.where(is_let, nb + 1, nb)

        # boundary: this column closes a field. Non-empty -> finalize the
        # hash and emit; empty at a space -> one more pending empty field;
        # empty at the end -> trailing, dropped.
        emit = (is_space | is_end) & (nbn > 0)
        tail_n = (nbn & 3).astype(jnp.uint32)
        if legacy:
            # hashUnsafeBytes: each tail byte gets a FULL mix round. Token
            # bytes are 'a'..'z' (< 0x80), so Java's sign extension is the
            # identity here.
            hfin = h1n
            for t in range(3):
                byte_t = (k1n >> jnp.uint32(8 * t)) & jnp.uint32(0xFF)
                hfin = jnp.where(tail_n > t, _mix_h1(hfin, _mix_k1(byte_t)),
                                 hfin)
        else:
            # hashUnsafeBytes2: the pending tail word mixes in once
            # (mix_k1(0) == 0, so the aligned case is the same expression).
            hfin = h1n ^ _mix_k1(k1n)
        hfin = _fmix(hfin, nbn.astype(jnp.uint32))
        hout = jax.lax.bitcast_convert_type(hfin, jnp.int32)

        pl.store(h_ref, (slice(None), pl.dslice(j, 1)),
                 jnp.where(emit, hout, 0))
        pl.store(w0_ref, (slice(None), pl.dslice(j, 1)),
                 jnp.where(emit, w0n, 0))
        pl.store(w1_ref, (slice(None), pl.dslice(j, 1)),
                 jnp.where(emit, w1n, 0))
        pl.store(tl_ref, (slice(None), pl.dslice(j, 1)),
                 jnp.where(emit, nbn, -1))

        empn = jnp.where(emit, emp + pend, emp)
        pendn = jnp.where(emit, zero_i, pend)
        pendn = jnp.where(is_space & (nbn == 0), pendn + 1, pendn)
        keptn = kept | is_let | is_space
        # cleaned row kept NOTHING: Java split("") returns [""] — exactly
        # one empty token, regardless of pending state.
        empn = jnp.where(is_end & ~keptn, jnp.ones_like(empn), empn)

        boundary = is_space | is_end
        return (jnp.where(boundary, seed_v, h1n),
                jnp.where(boundary, zero_u, k1n),
                jnp.where(boundary, zero_i, nbn),
                jnp.where(boundary, zero_i, w0n),
                jnp.where(boundary, zero_i, w1n),
                pendn, empn, keptn)

    init = (seed_v, zero_u, zero_i, zero_i, zero_i, zero_i, zero_i,
            jnp.zeros((nrows, 1), jnp.bool_))
    final = jax.lax.fori_loop(0, ncols, step, init)
    emp_ref[:, :] = final[6]


def tokenize_hash(classes: jax.Array, *, legacy: bool = False,
                  row_tile: int = ROW_TILE, interpret: bool = False
                  ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array,
                             jax.Array]:
    """Run the scan kernel over a (B, C) class tensor.

    Returns per-position streams ``(h_raw, w0, w1, tok_len)`` — each
    (B, C) int32, ``tok_len`` < 0 where no token ends — plus the per-row
    confirmed empty-token count (B, 1). Rows pad to the tile; columns pad
    to a lane multiple with CLS_NOP (a no-op for the scan).
    """
    b, c = classes.shape
    rt = min(row_tile, _round_up(max(b, 1), 8))
    b_pad = _round_up(max(b, 1), rt)
    c_pad = _round_up(c, 128)
    cls = jnp.zeros((b_pad, c_pad), jnp.int32).at[:b, :c].set(
        classes.astype(jnp.int32))
    outs = pl.pallas_call(
        partial(_scan_kernel, legacy=legacy),
        grid=(b_pad // rt,),
        in_specs=[pl.BlockSpec((rt, c_pad), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((rt, c_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, c_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, c_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, c_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rt, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b_pad, c_pad), jnp.int32),
            jax.ShapeDtypeStruct((b_pad, c_pad), jnp.int32),
            jax.ShapeDtypeStruct((b_pad, c_pad), jnp.int32),
            jax.ShapeDtypeStruct((b_pad, c_pad), jnp.int32),
            jax.ShapeDtypeStruct((b_pad, 1), jnp.int32),
        ],
        interpret=interpret,
    )(cls)
    h, w0, w1, tl, emp = outs
    return h[:b, :c], w0[:b, :c], w1[:b, :c], tl[:b, :c], emp[:b]


# ---------------------------------------------------------------------------
# stop-word table (host build + device probe share one hash)
# ---------------------------------------------------------------------------

def _probe_mix(w0: int, w1: int, ln: int) -> int:
    """The direct-map probe hash, in wrap-around uint32 arithmetic. The
    device twin below must stay expression-identical."""
    h = (w0 * 0x9E3779B1 + w1 * 0x85EBCA6B + ln * 0xC2B2AE35) & _MASK32
    h ^= h >> 15
    h = (h * 0x2C1B3C6D) & _MASK32
    h ^= h >> 12
    return h


def _probe_mix_device(w0, w1, ln):
    w0u = w0.astype(jnp.uint32)
    w1u = w1.astype(jnp.uint32)
    lnu = ln.astype(jnp.uint32)
    h = (w0u * jnp.uint32(0x9E3779B1) + w1u * jnp.uint32(0x85EBCA6B)
         + lnu * jnp.uint32(0xC2B2AE35))
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x2C1B3C6D)
    return h ^ (h >> 12)


def pack_token(word: str) -> Optional[Tuple[int, int, int]]:
    """(w0, w1, len) identity key of a cleaned token, or None when the word
    can never equal a cleaned token (chars outside [a-z]) — such stop words
    are unmatchable on the host path too, so dropping them is exact."""
    if any(not ("a" <= ch <= "z") for ch in word):
        return None
    w0 = w1 = 0
    for i, ch in enumerate(word[:_STOP_PACK_CHARS]):
        v = ord(ch) - 96
        if i < 6:
            w0 |= v << (5 * i)
        else:
            w1 |= v << (5 * (i - 6))
    return w0, w1, len(word)


def build_stop_table(words) -> Optional[Tuple[np.ndarray, bool]]:
    """Direct-mapped (size, 3) int32 stop table [w0, w1, len] + the
    empty-token flag, or None when the list cannot be represented exactly
    (a pure-[a-z] word longer than the pack width — the caller must fall
    back to host featurization rather than diverge silently).

    Size doubles until every eligible word lands in its own slot (the probe
    is just a hash; collisions are resolved by growing, so the table is
    collision-free by construction and one gather + compare per token is an
    EXACT membership test). Empty slots carry len = -1, matching no token.
    """
    empty_is_stop = False
    keys = []
    for w in words:
        if w == "":
            empty_is_stop = True
            continue
        key = pack_token(w)
        if key is None:
            continue                    # unmatchable on host too: exact drop
        if len(w) > _STOP_PACK_CHARS:
            return None                 # would ALIAS 12-char prefixes: refuse
        keys.append(key)
    size = 64
    while size <= _STOP_TABLE_MAX:
        slots = {}
        for key in keys:
            idx = _probe_mix(*key) & (size - 1)
            if idx in slots and slots[idx] != key:
                break
            slots[idx] = key
        else:
            tbl = np.full((size, 3), -1, np.int32)
            for idx, (w0, w1, ln) in slots.items():
                tbl[idx] = (w0, w1, ln)
            return tbl, empty_is_stop
        size *= 2
    return None


# ---------------------------------------------------------------------------
# count + pack (XLA post-pass, same jitted program)
# ---------------------------------------------------------------------------

def assemble_packed(h_raw, w0, w1, tok_len, empty_cnt, stop_table,
                    *, spec: FeaturizeSpec
                    ) -> Tuple[jax.Array, jax.Array]:
    """Token streams -> packed (B, 2, n_slots) int16 staging layout.

    Stop-word filter (exact table probe), bucket = nonNegativeMod(signed
    hash, F), per-row unique-bucket counts via sort + segment-sum, the host
    truncation rule past ``n_slots``, ids ascending with zero padding —
    the exact layout ``_pack_encoded`` ships. Also returns the per-row
    unique-bucket count (pre-truncation); serving callers drop it and jit
    DCE removes the extra outputs.
    """
    b, n = h_raw.shape
    f = spec.num_features
    sent = jnp.int32(f)                 # sorts past every real bucket

    idx = (_probe_mix_device(w0, w1, tok_len)
           & jnp.uint32(stop_table.shape[0] - 1)).astype(jnp.int32)
    probe = stop_table[idx]             # (B, N, 3) gather
    is_tok = tok_len >= 0
    is_stop = (is_tok & (probe[..., 0] == w0) & (probe[..., 1] == w1)
               & (probe[..., 2] == tok_len))
    keep = is_tok & ~is_stop

    bucket = jnp.remainder(h_raw, jnp.int32(f))    # floor-mod == nonNegativeMod
    stream = jnp.where(keep, bucket, sent)
    weight = keep.astype(jnp.int32)

    # The empty token "" rides as one extra (bucket, multiplicity) slot.
    emp = (jnp.zeros_like(empty_cnt) if spec.empty_is_stop
           else empty_cnt.astype(jnp.int32))
    stream = jnp.concatenate(
        [stream, jnp.where(emp > 0, jnp.int32(spec.empty_bucket), sent)],
        axis=1)
    weight = jnp.concatenate([weight, emp], axis=1)

    order = jnp.argsort(stream, axis=1)
    sb = jnp.take_along_axis(stream, order, axis=1)
    sw = jnp.take_along_axis(weight, order, axis=1)
    first = jnp.concatenate(
        [jnp.ones((b, 1), bool), sb[:, 1:] != sb[:, :-1]], axis=1)
    seg = jnp.cumsum(first.astype(jnp.int32), axis=1) - 1
    n_seg = n + 2                       # n+1 slots -> at most n+1 segments
    flat = (seg + jnp.arange(b, dtype=jnp.int32)[:, None] * n_seg).reshape(-1)
    counts = jnp.zeros((b * n_seg,), jnp.int32).at[flat].add(
        sw.reshape(-1)).reshape(b, n_seg)
    ids = jnp.zeros((b * n_seg,), jnp.int32).at[flat].max(
        sb.reshape(-1)).reshape(b, n_seg)
    valid = (ids < f) & (counts > 0)
    counts = jnp.where(valid, counts, 0)
    n_unique = jnp.sum(valid, axis=1)

    # Host truncation rule (featurize/tfidf._fill_python_rows): keep the
    # top-count buckets, ties resolving toward the LOWER bucket id — ids
    # are bucket-ascending here, so a stable sort on -count is exactly it.
    sel = jnp.argsort(-counts, axis=1, stable=True)[:, : spec.n_slots]
    sel_ids = jnp.take_along_axis(ids, sel, axis=1)
    sel_cnt = jnp.take_along_axis(counts, sel, axis=1)
    resort = jnp.argsort(jnp.where(sel_cnt > 0, sel_ids, sent), axis=1)
    out_ids = jnp.take_along_axis(sel_ids, resort, axis=1)
    out_cnt = jnp.take_along_axis(sel_cnt, resort, axis=1)
    out_ids = jnp.where(out_cnt > 0, out_ids, 0)
    if spec.binary:
        out_cnt = jnp.minimum(out_cnt, 1)
    out_cnt = jnp.minimum(out_cnt, 65535)
    if spec.n_slots > out_ids.shape[1]:     # tiny W: pad up to the contract
        pad = spec.n_slots - out_ids.shape[1]
        out_ids = jnp.pad(out_ids, ((0, 0), (0, pad)))
        out_cnt = jnp.pad(out_cnt, ((0, 0), (0, pad)))
    packed = jnp.stack(
        [out_ids.astype(jnp.int16),
         jax.lax.bitcast_convert_type(out_cnt.astype(jnp.uint16), jnp.int16)],
        axis=1)
    return packed, n_unique


def split_staged(staged: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(B, W+4) uint8 staging tensor -> ((B, W) bytes, (B,) int32 lengths).

    The per-row byte length rides little-endian in the LAST four columns so
    a micro-batch is ONE host->device transfer (the same single-crossing
    discipline as ``_pack_encoded``); 0xFFFFFFFF (-1) marks a padding row
    (featurize/device.py ``pack_staged``)."""
    byts = staged[:, :-4]
    tail = staged[:, -4:].astype(jnp.int32)
    lengths = (tail[:, 0] | (tail[:, 1] << 8) | (tail[:, 2] << 16)
               | (tail[:, 3] << 24))
    return byts, lengths


def featurize_bytes(staged: jax.Array, stop_table: jax.Array, *,
                    spec: FeaturizeSpec) -> Tuple[jax.Array, jax.Array]:
    """The full device featurize program: (B, W+4) uint8 staging tensor ->
    (packed (B, 2, n_slots) int16, per-row unique count). Composes under an
    outer jit with the packed scoring entries (models/pipeline.py), so
    bytes -> features -> probability is ONE device program."""
    byts, lengths = split_staged(staged)
    classes = byte_classes(byts, lengths)
    h, w0, w1, tl, emp = tokenize_hash(
        classes, legacy=spec.legacy, row_tile=spec.row_tile,
        interpret=spec.interpret)
    return assemble_packed(h, w0, w1, tl, emp, stop_table, spec=spec)


featurize_bytes_jit = jax.jit(featurize_bytes, static_argnames=("spec",))


# ---------------------------------------------------------------------------
# capability probes
# ---------------------------------------------------------------------------

def auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


@lru_cache(maxsize=None)
def interpreter_can_run() -> bool:
    """Environment-only canary (PR 9 style): can this jax's Pallas
    interpreter run the scan kernel's feature set — ``fori_loop`` carrying
    state, predicated ``pl.store`` to a dynamic column, uint32 wrap-around
    arithmetic? Probes a miniature kernel against a host-computed
    expectation; any exception or mismatch means the kernel tests skip and
    the serving probe falls back to host featurization with an honest
    ``featurize_path``."""
    try:
        def kern(x_ref, o_ref):
            def step(j, acc):
                v = x_ref[:, pl.dslice(j, 1)].astype(jnp.uint32)
                acc = acc * jnp.uint32(0x9E3779B1) + v
                pl.store(o_ref, (slice(None), pl.dslice(j, 1)),
                         jax.lax.bitcast_convert_type(acc, jnp.int32))
                return acc
            jax.lax.fori_loop(0, x_ref.shape[1], step,
                              jnp.zeros((x_ref.shape[0], 1), jnp.uint32))

        x = np.arange(8, dtype=np.int32).reshape(2, 4)
        out = pl.pallas_call(
            kern, out_shape=jax.ShapeDtypeStruct((2, 4), jnp.int32),
            interpret=True)(jnp.asarray(x))
        want = np.zeros((2, 4), np.uint32)
        for r in range(2):
            acc = 0
            for j in range(4):
                acc = (acc * 0x9E3779B1 + int(x[r, j])) & _MASK32
                want[r, j] = acc
        return bool(np.array_equal(np.asarray(out).view(np.uint32), want))
    except Exception:  # noqa: BLE001 — any refusal means "no"
        return False
