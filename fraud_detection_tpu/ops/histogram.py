"""Pallas TPU kernels for histogram tree building.

The tree trainer's hot op is the per-level (node, feature, bin) statistics
histogram over the sharded row set (models/train_trees.py:158-162 — the
XLA path vmaps a segment-sum over all 10k features). On TPU the idiomatic
formulation is a matmul, not a scatter: for a row tile,

    hist[f*NB+b, l*K+k] = sum_r  onehot(bins[r,f]==b) * onehot(node[r]==l) * stats[r,k]
                        =        multihot_bins^T  @  (node_onehot (x) stats)

— one (F_t*NB, R) @ (R, L*K) contraction per (feature-tile, row-tile) grid
cell, accumulated over row tiles in VMEM. The scatter becomes MXU work at
full systolic utilization; this is the same reformulation the reference's
XGBoost applies on GPU with atomics, done the TPU way (BASELINE.json:
"histogram build ... becomes Pallas kernels").

The split-gain scan (cumsum over bins + impurity gain + argmax — the
per-level decision) ships here too as a fused VPU kernel.

Both kernels run under ``interpret=True`` off-TPU so the CPU test mesh
exercises them; ``auto_interpret()`` picks per backend.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# ---------------------------------------------------------------------------
# histogram kernel
# ---------------------------------------------------------------------------

def _hist_kernel(bins_ref, local_ref, stats_ref, out_ref, *, n_bins: int,
                 n_nodes: int, k: int):
    """One (feature-tile, row-tile) cell: out += multihot^T @ (node (x) stats)."""
    r_idx = pl.program_id(1)

    @pl.when(r_idx == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = bins_ref[:]                         # (R, Ft) int32
    local = local_ref[:, 0]                    # (R,) int32; >= n_nodes -> inactive
    stats = stats_ref[:]                       # (R, K) f32

    R, Ft = bins.shape
    # multi-hot over the flattened (feature-in-tile, bin) axis
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (R, Ft, n_bins), 2)
    multihot = (bin_iota == bins[:, :, None]).reshape(R, Ft * n_bins)
    # node-onehot (x) stats -> (R, L*K); inactive rows are all-zero
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (R, n_nodes), 1)
    node_onehot = (node_iota == local[:, None]).astype(stats.dtype)
    ns = (node_onehot[:, :, None] * stats[:, None, :]).reshape(R, n_nodes * k)

    out_ref[:] += jax.lax.dot_general(
        multihot.astype(stats.dtype), ns,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@partial(jax.jit, static_argnames=("n_nodes", "n_bins", "row_tile",
                                   "feature_tile", "interpret"))
def node_feature_bin_histogram(
    bins: jax.Array,      # (N, F) int32 bin ids
    local: jax.Array,     # (N,) int32 node position within the level; >= n_nodes = skip
    stats: jax.Array,     # (N, K) f32 per-row statistics (weights folded in)
    *,
    n_nodes: int,
    n_bins: int,
    row_tile: int = 512,
    feature_tile: int = 32,
    interpret: bool = False,
) -> jax.Array:
    """(n_nodes, F, n_bins, K) statistics histogram via the Pallas kernel."""
    n, f = bins.shape
    k = stats.shape[-1]
    n_pad = _round_up(max(n, 1), row_tile)
    f_pad = _round_up(max(f, 1), feature_tile)
    bins_p = jnp.zeros((n_pad, f_pad), jnp.int32)
    bins_p = bins_p.at[:n, :f].set(bins)
    local_p = jnp.full((n_pad, 1), n_nodes, jnp.int32).at[:n, 0].set(local)
    stats_p = jnp.zeros((n_pad, k), stats.dtype).at[:n].set(stats)

    grid = (f_pad // feature_tile, n_pad // row_tile)
    out = pl.pallas_call(
        partial(_hist_kernel, n_bins=n_bins, n_nodes=n_nodes, k=k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile, feature_tile), lambda fi, ri: (ri, fi),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, 1), lambda fi, ri: (ri, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, k), lambda fi, ri: (ri, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((feature_tile * n_bins, n_nodes * k),
                               lambda fi, ri: (fi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f_pad * n_bins, n_nodes * k), jnp.float32),
        interpret=interpret,
    )(bins_p, local_p, stats_p)

    hist = out.reshape(f_pad, n_bins, n_nodes, k)[:f]
    return hist.transpose(2, 0, 1, 3)  # (L, F, NB, K)


def histogram_reference(bins, local, stats, *, n_nodes: int, n_bins: int) -> jax.Array:
    """XLA segment-sum formulation (models/train_trees.py:158-162 shape)."""
    valid = local < n_nodes
    seg_local = jnp.where(valid, local, n_nodes)

    def one_feature(fbins):
        seg = jnp.where(valid, seg_local * n_bins + fbins, n_nodes * n_bins)
        return jax.ops.segment_sum(stats, seg, num_segments=n_nodes * n_bins + 1)[:-1]

    hist = jax.vmap(one_feature, in_axes=1)(bins)       # (F, L*NB, K)
    f = bins.shape[1]
    return hist.reshape(f, n_nodes, n_bins, -1).transpose(1, 0, 2, 3)


# ---------------------------------------------------------------------------
# split-gain scan kernel
# ---------------------------------------------------------------------------

def _gain_kernel(hist_ref, total_ref, best_idx_ref, best_gain_ref, *,
                 n_bins: int, criterion: str, reg_lambda: float,
                 min_child_weight: float):
    """One node: cumsum over bins, impurity gain, argmax over (F, NB-1)."""
    hist = hist_ref[0].astype(jnp.float32)        # block (1, F, NB*K) -> (F, NB*K)
    F = hist.shape[0]
    k = hist.shape[1] // n_bins
    hist = hist.reshape(F, n_bins, k)
    total = total_ref[0].astype(jnp.float32)      # (K,)

    left = jnp.cumsum(hist, axis=1)               # (F, NB, K)
    right = total[None, None, :] - left
    if criterion == "gini":
        def gini_sum(s):
            cnt = jnp.sum(s, axis=-1)
            sq = jnp.sum(s * s, axis=-1)
            return cnt - sq / jnp.maximum(cnt, 1e-12), cnt
        (g_l, n_l) = gini_sum(left)
        (g_r, n_r) = gini_sum(right)
        (g_p, n_p) = gini_sum(total[None, None, :])
        gain = (g_p - g_l - g_r) / jnp.maximum(n_p, 1e-12)
        valid = (n_l > 0) & (n_r > 0)
    else:  # xgb second-order gain; stats layout (grad, hess, count)
        gl, hl, cl = left[..., 0], left[..., 1], left[..., 2]
        gr, hr, cr = right[..., 0], right[..., 1], right[..., 2]
        gp, hp = total[0], total[1]
        score = lambda g, h: (g * g) / (h + reg_lambda)
        gain = 0.5 * (score(gl, hl) + score(gr, hr) - score(gp, hp))
        valid = (hl >= min_child_weight) & (hr >= min_child_weight) & \
                (cl > 0) & (cr > 0)
    gain = jnp.where(valid, gain, -jnp.inf)[:, : n_bins - 1]   # last bin: no right
    flat = gain.reshape(-1)
    best = jnp.argmax(flat)
    best_idx_ref[0, 0] = best.astype(jnp.int32)
    best_gain_ref[0, 0] = flat[best]


@partial(jax.jit, static_argnames=("criterion", "n_bins", "reg_lambda",
                                   "min_child_weight", "interpret"))
def best_splits(
    hist: jax.Array,       # (L, F, NB, K)
    totals: jax.Array,     # (L, K)
    *,
    criterion: str = "gini",
    n_bins: int = 32,
    reg_lambda: float = 1.0,
    min_child_weight: float = 1e-6,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per node: (best_feature, best_bin, best_gain) fused on the VPU."""
    L, F, NB, K = hist.shape
    flat_hist = hist.reshape(L, F, NB * K)
    idx, gain = pl.pallas_call(
        partial(_gain_kernel, n_bins=NB, criterion=criterion,
                reg_lambda=reg_lambda, min_child_weight=min_child_weight),
        grid=(L,),
        in_specs=[
            pl.BlockSpec((1, F, NB * K), lambda l: (l, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, K), lambda l: (l, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda l: (l, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda l: (l, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L, 1), jnp.int32),
            jax.ShapeDtypeStruct((L, 1), jnp.float32),
        ],
        interpret=interpret,
    )(flat_hist, totals)
    idx = idx[:, 0]
    return (idx // (NB - 1)).astype(jnp.int32), (idx % (NB - 1)).astype(jnp.int32), gain[:, 0]
