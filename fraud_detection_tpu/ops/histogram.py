"""Pallas TPU kernels for histogram tree building.

The tree trainer's hot op is the per-level (node, feature, bin) statistics
histogram over the sharded row set (models/train_trees.py:158-162 — the
XLA path vmaps a segment-sum over all 10k features). On TPU the idiomatic
formulation is a matmul, not a scatter: for a row tile,

    hist[f*NB+b, l*K+k] = sum_r  onehot(bins[r,f]==b) * onehot(node[r]==l) * stats[r,k]
                        =        multihot_bins^T  @  (node_onehot (x) stats)

— one (F_t*NB, R) @ (R, L*K) contraction per (feature-tile, row-tile) grid
cell, accumulated over row tiles in VMEM. The scatter becomes MXU work at
full systolic utilization; this is the same reformulation the reference's
XGBoost applies on GPU with atomics, done the TPU way (BASELINE.json:
"histogram build ... becomes Pallas kernels").

The split-gain scan (cumsum over bins + impurity gain + argmax — the
per-level decision) ships here too as a fused VPU kernel.

Both kernels run under ``interpret=True`` off-TPU so the CPU test mesh
exercises them; ``auto_interpret()`` picks per backend.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


@lru_cache(maxsize=None)
def _host_callbacks_supported() -> bool:
    """Some PJRT backends (the tunneled axon TPU) cannot EXECUTE programs
    containing host callbacks (jax.debug.print et al.) — and reject them at
    run time, not compile time. Probed once with a never-taken cond, under
    ``ensure_compile_time_eval`` so the probe runs eagerly even when called
    mid-trace (a plain call there would inline the callback into the outer
    program: debug effects defeat DCE, poisoning the caller's jit). Where
    False, the exact_int8 contract diagnostic degrades to silent saturation
    (the kernel's clip still prevents int8 wraparound)."""
    try:
        def probe(x):
            jax.lax.cond(x > 0,
                         lambda v: jax.debug.print("{v}", v=v),
                         lambda v: None, x)
            return x

        with jax.ensure_compile_time_eval():
            # Host fetch, not block_until_ready: the axon tunnel acks
            # dispatches asynchronously, so only materializing the value
            # guarantees the runtime's rejection surfaces inside this try.
            # flightcheck: ignore[FC201] — one-shot capability probe, result cached for the process
            float(jax.device_get(jax.jit(probe)(jnp.zeros(()))))
        return True
    except Exception:  # noqa: BLE001 — any refusal means "no"
        return False


# Default tile grid — OWNED here; the trainers' pre-padding imports these so
# the aligned no-copy fast path can never silently drift from the kernel.
ROW_TILE = 256
FEATURE_TILE = 128


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# ---------------------------------------------------------------------------
# histogram kernel
# ---------------------------------------------------------------------------

def node_feature_bin_histogram(
    bins: jax.Array,      # (N, F) int32 bin ids
    local: jax.Array,     # (N,) int32 node position within the level; >= n_nodes = skip
    stats: jax.Array,     # (N, K) f32 per-row statistics (weights folded in)
    *,
    n_nodes: int,
    n_bins: int,
    row_tile: int = ROW_TILE,
    feature_tile: int = FEATURE_TILE,
    interpret: bool = False,
    exact_int8: bool = False,
) -> jax.Array:
    """(n_nodes, F, n_bins, K) statistics histogram via the Pallas kernel —
    the T=1 case of ``node_feature_bin_histogram_multi`` (unit weights are
    exact, so delegating costs one multiply by 1.0 and keeps a single
    kernel to maintain)."""
    hist = node_feature_bin_histogram_multi(
        bins, local[None, :], jnp.ones((1, local.shape[0]), jnp.float32),
        stats, n_nodes=n_nodes, n_bins=n_bins, row_tile=row_tile,
        feature_tile=feature_tile, interpret=interpret,
        exact_int8=exact_int8)
    return hist[0]


def _hist_kernel_multi(bins_ref, b_of_c_ref, locals_ref, weights_ref,
                       stats_ref, out_ref, *, n_bins: int, n_nodes: int,
                       k: int, n_trees: int, exact_int8: bool):
    """One (feature-tile, row-tile) cell for T trees sharing ``bins``:
    out += [node (x) stats (x) weights]^T @ multihot.

    Mosaic constraints + MXU economics shape this kernel:

    * No minor-dim reshape exists, so the flat bucket axis uses the
      (bin, feature-in-tile) order that ``pltpu.repeat`` (tile-concat
      semantics) produces directly — column c <-> (b = c // Ft, f = c % Ft)
      — and the khatri-rao node (x) stats matrix is built by sublane-axis
      concatenation instead of a 3D reshape. The host wrapper untangles.
    * ``b_of_c`` (the bin id of each flat column — identical for every tile)
      arrives as a (1, C) input instead of a per-cell iota+divide.
    * The dot runs TRANSPOSED — (T*K*L, R) @ (R, C) — so the 4096-wide
      bucket axis lands on lanes: the MXUs parallelize over lanes, and
      T*K*L on lanes would leave most idle. Fusing T trees builds the
      expensive multihot (the kernel's dominant cost) ONCE per cell instead
      of per tree, and fills MXU lanes a single tree leaves idle at shallow
      levels. Output rows: t*(K*L) + kk*L + l.
    * ``exact_int8`` (class-count statistics — gini DT/RF): stats, weights,
      multihot and the khatri-rao matrix are all small non-negative ints, so
      the whole contraction runs as ONE int8 MXU pass accumulating int32 —
      bit-exact (stronger than any float formulation) at the MXU's double
      int8 rate. The f32 path splits stats hi/lo into two bf16 passes (~16
      mantissa bits, accumulated in f32): single-pass bf16 rounds to 8 bits
      — enough error (~1e-2 relative) to flip split argmaxes vs the XLA
      path — while HIGHEST costs 6 passes for precision the argmax doesn't
      need. The 0/1 multihot is exact in bf16.
    """
    r_idx = pl.program_id(1)

    @pl.when(r_idx == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = bins_ref[:]                         # (R, Ft) int32
    R, Ft = bins.shape
    bins_rep = pltpu.repeat(bins, n_bins, axis=1)                  # (R, C)
    eq = bins_rep == b_of_c_ref[:]
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (n_nodes, R), 0)
    dims = (((1,), (0,)), ((), ()))

    # Khatri-rao build runs in f32 on both paths (Mosaic has no int8
    # elementwise multiply; f32 is exact for the int path's magnitudes).
    parts = []
    for t in range(n_trees):
        local_t = locals_ref[t : t + 1, :]                         # (1, R)
        w_t = weights_ref[t : t + 1, :]                            # (1, R)
        onehot_t = (node_iota == local_t).astype(jnp.float32)      # (L, R)
        for kk in range(k):
            parts.append(onehot_t * (stats_ref[kk : kk + 1, :] * w_t))
    ns = jnp.concatenate(parts, axis=0)                            # (T*K*L, R)

    if exact_int8:
        # stats*w <= 127 (one-hot class counts x Poisson weights) — the
        # trainer guarantees the range, so the int8 cast is exact and the
        # contraction is ONE int8 MXU pass accumulating exact int32. The
        # clip saturates (instead of silently wrapping to negative counts)
        # if a future caller breaks the contract; the jitted wrapper
        # additionally reports the violation (jax.debug.print).
        out_ref[:] += jax.lax.dot_general(
            jnp.clip(ns, 0.0, 127.0).astype(jnp.int8), eq.astype(jnp.int8),
            dims, preferred_element_type=jnp.int32)
        return

    multihot = eq.astype(jnp.bfloat16)
    ns_hi = ns.astype(jnp.bfloat16)
    ns_lo = (ns - ns_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(ns_hi, multihot, dims,
                              preferred_element_type=jnp.float32)
    acc = acc + jax.lax.dot_general(ns_lo, multihot, dims,
                                    preferred_element_type=jnp.float32)
    out_ref[:] += acc


@partial(jax.jit, static_argnames=("n_nodes", "n_bins", "row_tile",
                                   "feature_tile", "interpret", "exact_int8"))
def node_feature_bin_histogram_multi(
    bins: jax.Array,      # (N, F) int32 bin ids, SHARED by all trees
    locals_: jax.Array,   # (T, N) int32 per-tree node position; >= n_nodes = skip
    weights: jax.Array,   # (T, N) f32 per-tree bootstrap weights
    stats: jax.Array,     # (N, K) f32 per-row statistics (weights NOT folded)
    *,
    n_nodes: int,
    n_bins: int,
    row_tile: int = ROW_TILE,
    feature_tile: int = FEATURE_TILE,
    interpret: bool = False,
    exact_int8: bool = False,
) -> jax.Array:
    """(T, n_nodes, F, n_bins, K) histograms for a chunk of trees sharing
    one binned matrix — the forest trainer's per-level hot op.

    ``exact_int8``: caller promises stats and weights are non-negative
    integers with per-row products < 128 (class one-hots x Poisson bootstrap
    weights — the gini trainers). The kernel then runs ONE int8 MXU pass
    with exact int32 accumulation instead of two bf16 passes: ~2x faster and
    bit-exact. Output is f32 either way (exact for the int path: every count
    is far below 2^24)."""
    n, f = bins.shape
    t, k = locals_.shape[0], stats.shape[-1]
    n_pad = _round_up(max(n, 1), row_tile)
    f_pad = _round_up(max(f, 1), feature_tile)
    bins = bins.astype(jnp.int32)  # dtype contract independent of alignment
    if n_pad == n and f_pad == f:
        # Aligned input: skip the pad — the zeros+set below copies the FULL
        # (N, F) matrix (GBs of pure HBM copy per level at bench scale), so
        # the trainers pre-pad once and hit this branch every level.
        bins_p = bins
    else:
        bins_p = jnp.zeros((n_pad, f_pad), jnp.int32)
        bins_p = bins_p.at[:n, :f].set(bins)
    locals_p = jnp.full((t, n_pad), n_nodes, jnp.int32).at[:, :n].set(locals_)
    weights_p = jnp.zeros((t, n_pad), jnp.float32).at[:, :n].set(
        weights.astype(jnp.float32))
    stats_p = jnp.zeros((k, n_pad), jnp.float32).at[:, :n].set(
        stats.T.astype(jnp.float32))
    b_of_c = (jnp.arange(feature_tile * n_bins, dtype=jnp.int32)
              // feature_tile)[None, :]

    if exact_int8 and _host_callbacks_supported():
        # Loud contract check: the int8 MXU path is exact only for
        # stats*weight products in [0, 127]. The exact per-row bound
        # max_r(max_k stats[k,r] * max_t w[t,r]) is as cheap as the global
        # maxima and never false-positives across rows; negatives violate
        # the non-negativity half of the contract (the kernel clip would
        # silently zero them). Violations print a diagnostic (the kernel
        # saturates to [0, 127] rather than wrapping).
        bound = jnp.max(jnp.max(stats_p, axis=0) * jnp.max(weights_p, axis=0))
        negative = jnp.minimum(jnp.min(stats_p), jnp.min(weights_p))
        # Negated-complement predicates so NaN operands (which compare False
        # both ways) trip the diagnostic instead of slipping past it.
        jax.lax.cond(
            ~(bound <= 127.0) | ~(negative >= 0.0),
            lambda b, neg: jax.debug.print(
                "ops.histogram exact_int8 contract violated: per-row "
                "stats*weight bound {b}, min operand {neg} — products are "
                "clipped to [0, 127] (use the bf16 path for unbounded or "
                "signed stats)", b=b, neg=neg),
            lambda b, neg: None, bound, negative)

    grid = (f_pad // feature_tile, n_pad // row_tile)
    out = pl.pallas_call(
        partial(_hist_kernel_multi, n_bins=n_bins, n_nodes=n_nodes, k=k,
                n_trees=t, exact_int8=exact_int8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile, feature_tile), lambda fi, ri: (ri, fi),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, feature_tile * n_bins), lambda fi, ri: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t, row_tile), lambda fi, ri: (0, ri),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((t, row_tile), lambda fi, ri: (0, ri),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, row_tile), lambda fi, ri: (0, ri),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t * k * n_nodes, feature_tile * n_bins),
                               lambda fi, ri: (0, fi),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (t * k * n_nodes, f_pad * n_bins),
            jnp.int32 if exact_int8 else jnp.float32),
        interpret=interpret,
    )(bins_p, b_of_c, locals_p, weights_p, stats_p)

    # Untangle: row = t*(K*L) + kk*L + l, col = tile*(NB*Ft) + b*Ft + f_in
    # -> (T, L, F, NB, K).
    n_tiles = f_pad // feature_tile
    hist = out.reshape(t, k, n_nodes, n_tiles, n_bins, feature_tile)
    hist = hist.transpose(0, 2, 3, 5, 4, 1).reshape(
        t, n_nodes, f_pad, n_bins, k)
    return hist[:, :, :f].astype(jnp.float32)


def histogram_reference(bins, local, stats, *, n_nodes: int, n_bins: int) -> jax.Array:
    """XLA segment-sum formulation (models/train_trees.py:158-162 shape)."""
    valid = local < n_nodes
    seg_local = jnp.where(valid, local, n_nodes)

    def one_feature(fbins):
        seg = jnp.where(valid, seg_local * n_bins + fbins, n_nodes * n_bins)
        return jax.ops.segment_sum(stats, seg, num_segments=n_nodes * n_bins + 1)[:-1]

    hist = jax.vmap(one_feature, in_axes=1)(bins)       # (F, L*NB, K)
    f = bins.shape[1]
    return hist.reshape(f, n_nodes, n_bins, -1).transpose(1, 0, 2, 3)


# ---------------------------------------------------------------------------
# split-gain scan kernel
# ---------------------------------------------------------------------------

def _gain_kernel(hist_ref, total_ref, best_idx_ref, best_gain_ref, *,
                 n_bins: int, n_stats: int, criterion: str, reg_lambda: float,
                 min_child_weight: float):
    """One (node, feature-tile) cell: cumulative-left stats, impurity gain,
    argmax over the tile's (Ft, NB-1) candidates.

    All intermediates are 2D (Ft, NB) per statistic — Mosaic has no
    minor-dim reshape, so the K statistics arrive pre-sliced on a leading
    axis and the bin-cumsum is an upper-triangular matmul (MXU work; exact
    for the 0/1 and small-count magnitudes involved). Totals ride in SMEM as
    scalars. The per-tile argmax is recovered as min(position where gain ==
    max), matching XLA's first-occurrence argmax tie rule in row-major
    order; the host wrapper reduces across tiles (features are tiled so huge
    F doesn't overflow VMEM — the whole (F, NB, K) slab at F=10000 needs
    >30MB of intermediates).
    """
    f_idx = pl.program_id(1)
    nb = n_bins
    # inclusive prefix over bins: left = hist @ upper_tri  (NB, NB)
    tri_r = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 0)
    tri_c = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 1)
    tri = (tri_r <= tri_c).astype(jnp.float32)

    left = []
    total = []
    for kk in range(n_stats):
        h = hist_ref[0, kk].astype(jnp.float32)          # (F, NB)
        left.append(jax.lax.dot_general(
            h, tri, dimension_numbers=(((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32))
        total.append(total_ref[0, 0, kk])                # SMEM scalar
    right = [t - l for l, t in zip(left, total)]

    if criterion == "gini":
        def gini_sum(stats_2d):
            cnt = stats_2d[0]
            sq = stats_2d[0] * stats_2d[0]
            for s in stats_2d[1:]:
                cnt = cnt + s
                sq = sq + s * s
            return cnt - sq / jnp.maximum(cnt, 1e-12), cnt
        g_l, n_l = gini_sum(left)
        g_r, n_r = gini_sum(right)
        cnt_p = total[0]
        sq_p = total[0] * total[0]
        for t in total[1:]:
            cnt_p = cnt_p + t
            sq_p = sq_p + t * t
        g_p = cnt_p - sq_p / jnp.maximum(cnt_p, 1e-12)
        gain = (g_p - g_l - g_r) / jnp.maximum(cnt_p, 1e-12)
        valid = (n_l > 0) & (n_r > 0)
    else:  # xgb second-order gain; stats layout (grad, hess, count)
        gl, hl, cl = left[0], left[1], left[2]
        gr, hr, cr = right[0], right[1], right[2]
        gp, hp = total[0], total[1]
        score = lambda g, h: (g * g) / (h + reg_lambda)
        gain = 0.5 * (score(gl, hl) + score(gr, hr) - score(gp, hp))
        valid = (hl >= min_child_weight) & (hr >= min_child_weight) & \
                (cl > 0) & (cr > 0)

    f = gain.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (f, nb), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (f, nb), 0)
    in_range = col < nb - 1                              # last bin: no right side
    gain = jnp.where(valid & in_range, gain, -jnp.inf)
    best = jnp.max(gain)
    pos = row * (nb - 1) + col                           # tile-local position
    pos = jnp.where((gain == best) & in_range, pos, jnp.int32(2**30))
    best_idx_ref[0, 0, f_idx] = jnp.min(pos)
    best_gain_ref[0, 0, f_idx] = best


@partial(jax.jit, static_argnames=("criterion", "n_bins", "reg_lambda",
                                   "min_child_weight", "feature_tile",
                                   "interpret"))
def best_splits(
    hist: jax.Array,       # (L, F, NB, K)
    totals: jax.Array,     # (L, K)
    *,
    criterion: str = "gini",
    n_bins: int = 32,
    reg_lambda: float = 1.0,
    min_child_weight: float = 1e-6,
    feature_tile: int = 1024,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per node: (best_feature, best_bin, best_gain) fused on the VPU.

    Features are processed in tiles of ``feature_tile``; each grid cell emits
    its tile's (first-occurrence) best, and a cheap XLA reduction combines
    tiles — argmax over tile bests picks the lowest tile on ties, which
    together with the in-tile min-position rule reproduces XLA's flat
    row-major first-occurrence argmax exactly.
    """
    L, F, NB, K = hist.shape
    ft = min(feature_tile, F)
    f_pad = _round_up(F, ft)
    hist_k = hist.transpose(0, 3, 1, 2)                  # (L, K, F, NB)
    if f_pad != F:
        # Padded features carry all-zero stats: empty children/hessians make
        # every candidate invalid (-inf), so padding never wins.
        hist_k = jnp.pad(hist_k, ((0, 0), (0, 0), (0, f_pad - F), (0, 0)))
    n_tiles = f_pad // ft
    totals3 = totals.reshape(L, 1, K)
    idx_t, gain_t = pl.pallas_call(
        partial(_gain_kernel, n_bins=NB, n_stats=K, criterion=criterion,
                reg_lambda=reg_lambda, min_child_weight=min_child_weight),
        grid=(L, n_tiles),
        in_specs=[
            pl.BlockSpec((1, K, ft, NB), lambda l, fi: (l, 0, fi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, K), lambda l, fi: (l, 0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, n_tiles), lambda l, fi: (l, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, n_tiles), lambda l, fi: (l, 0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L, 1, n_tiles), jnp.int32),
            jax.ShapeDtypeStruct((L, 1, n_tiles), jnp.float32),
        ],
        interpret=interpret,
    )(hist_k, totals3)
    idx_t = idx_t[:, 0, :]                               # (L, T) tile-local pos
    gain_t = gain_t[:, 0, :]                             # (L, T)
    t_star = jnp.argmax(gain_t, axis=1)                  # ties -> lowest tile
    best_gain = jnp.take_along_axis(gain_t, t_star[:, None], 1)[:, 0]
    idx = jnp.take_along_axis(idx_t, t_star[:, None], 1)[:, 0]
    best_f = t_star.astype(jnp.int32) * ft + (idx // (NB - 1)).astype(jnp.int32)
    return best_f, (idx % (NB - 1)).astype(jnp.int32), best_gain
