from fraud_detection_tpu.parallel.mesh import (
    DATA_AXIS,
    FEATURE_AXIS,
    batch_sharding,
    feature_sharding,
    make_mesh,
    pad_to_multiple,
    replicated,
    shard_rows,
)

__all__ = [
    "DATA_AXIS", "FEATURE_AXIS", "batch_sharding", "feature_sharding",
    "make_mesh", "pad_to_multiple", "replicated", "shard_rows",
]
