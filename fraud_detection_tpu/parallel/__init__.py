from fraud_detection_tpu.parallel.mesh import (
    DATA_AXIS,
    FEATURE_AXIS,
    batch_sharding,
    feature_sharding,
    global_batch_from_local,
    initialize_distributed,
    make_hybrid_mesh,
    make_mesh,
    pad_to_multiple,
    replicated,
    shard_rows,
)

__all__ = [
    "DATA_AXIS", "FEATURE_AXIS", "batch_sharding", "feature_sharding",
    "make_mesh", "make_hybrid_mesh", "initialize_distributed",
    "global_batch_from_local", "pad_to_multiple", "replicated", "shard_rows",
]
