"""Device-mesh construction and sharding helpers.

The framework's distributed layer: everything the reference delegated to Spark
executors / XGBoost Rabit allreduce (SURVEY.md §2.4) maps here onto a
``jax.sharding.Mesh`` with named axes and XLA collectives over ICI:

  axis "data"    — rows (dialogues): data parallelism for training batches and
                   streaming micro-batches. Gradient/histogram reductions
                   become psums over this axis (the Rabit-allreduce analogue).
  axis "feature" — TF-IDF feature dimension: used by histogram tree building
                   to split the 10k-feature scan across chips.

On a single host this works against real TPU chips or the CPU
``--xla_force_host_platform_device_count`` virtual mesh; on multi-host pods the
same named-axis code spans DCN via jax.distributed without change — that is the
point of expressing communication as named-axis collectives instead of
explicit endpoints.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def make_mesh(n_devices: Optional[int] = None,
              data_parallel: Optional[int] = None,
              feature_parallel: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, feature) mesh over the available devices.

    Defaults to all devices on the data axis — the right layout for this
    workload, where models are tiny and rows are plentiful.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if data_parallel is None:
        if n % feature_parallel:
            raise ValueError(f"{n} devices not divisible by feature_parallel={feature_parallel}")
        data_parallel = n // feature_parallel
    if data_parallel * feature_parallel != n:
        raise ValueError(
            f"data_parallel({data_parallel}) * feature_parallel({feature_parallel}) != {n}")
    grid = np.asarray(devs).reshape(data_parallel, feature_parallel)
    return Mesh(grid, (DATA_AXIS, FEATURE_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the data axis, features replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, None))


def feature_sharding(mesh: Mesh) -> NamedSharding:
    """Feature-dimension sharding for (F,)-shaped or (B, F) arrays' last axis."""
    return NamedSharding(mesh, P(None, FEATURE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def shard_rows(x: np.ndarray, mesh: Mesh) -> jax.Array:
    """Pad rows to a data-axis multiple and device_put with row sharding.

    Padding rows are zeros; callers carry an explicit validity mask when the
    padded rows must not contribute (losses, metrics).
    """
    dp = mesh.shape[DATA_AXIS]
    padded = pad_to_multiple(x.shape[0], dp)
    if padded != x.shape[0]:
        pad_width = [(0, padded - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        x = np.pad(x, pad_width)
    return jax.device_put(x, batch_sharding(mesh) if x.ndim > 1
                          else NamedSharding(mesh, P(DATA_AXIS)))
