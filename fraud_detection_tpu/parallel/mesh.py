"""Device-mesh construction and sharding helpers.

The framework's distributed layer: everything the reference delegated to Spark
executors / XGBoost Rabit allreduce (SURVEY.md §2.4) maps here onto a
``jax.sharding.Mesh`` with named axes and XLA collectives over ICI:

  axis "data"    — rows (dialogues): data parallelism for training batches and
                   streaming micro-batches. Gradient/histogram reductions
                   become psums over this axis (the Rabit-allreduce analogue).
  axis "feature" — TF-IDF feature dimension: used by histogram tree building
                   to split the 10k-feature scan across chips.

On a single host this works against real TPU chips or the CPU
``--xla_force_host_platform_device_count`` virtual mesh; on multi-host pods the
same named-axis code spans DCN via jax.distributed without change — that is the
point of expressing communication as named-axis collectives instead of
explicit endpoints.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def make_mesh(n_devices: Optional[int] = None,
              data_parallel: Optional[int] = None,
              feature_parallel: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (data, feature) mesh over the available devices.

    Defaults to all devices on the data axis — the right layout for this
    workload, where models are tiny and rows are plentiful.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if data_parallel is None:
        if n % feature_parallel:
            raise ValueError(f"{n} devices not divisible by feature_parallel={feature_parallel}")
        data_parallel = n // feature_parallel
    if data_parallel * feature_parallel != n:
        raise ValueError(
            f"data_parallel({data_parallel}) * feature_parallel({feature_parallel}) != {n}")
    grid = np.asarray(devs).reshape(data_parallel, feature_parallel)
    return Mesh(grid, (DATA_AXIS, FEATURE_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the data axis, features replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, None))


def feature_sharding(mesh: Mesh) -> NamedSharding:
    """Feature-dimension sharding for (F,)-shaped or (B, F) arrays' last axis."""
    return NamedSharding(mesh, P(None, FEATURE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def shard_rows(x: np.ndarray, mesh: Mesh) -> jax.Array:
    """Pad rows to a data-axis multiple and device_put with row sharding.

    Padding rows are zeros; callers carry an explicit validity mask when the
    padded rows must not contribute (losses, metrics).
    """
    dp = mesh.shape[DATA_AXIS]
    padded = pad_to_multiple(x.shape[0], dp)
    if padded != x.shape[0]:
        pad_width = [(0, padded - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        x = np.pad(x, pad_width)
    return jax.device_put(x, batch_sharding(mesh) if x.ndim > 1
                          else NamedSharding(mesh, P(DATA_AXIS)))


# ---------------------------------------------------------------------------
# Multi-host (DCN) support
# ---------------------------------------------------------------------------

def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Multi-host bootstrap over DCN (the NCCL/MPI-rendezvous analogue).

    Reads ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` when arguments are omitted; a no-op (returns False)
    when the job is single-process, so single-host code paths never pay for
    it. After this, ``jax.devices()`` spans every host's chips and the
    named-axis collectives in this package ride ICI within a host and DCN
    across hosts with no further code changes.
    """
    import os

    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        env_np = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env_np) if env_np is not None else None
    if process_id is None:
        env_pid = os.environ.get("JAX_PROCESS_ID")
        # Stays None when unset: jax.distributed.initialize auto-detects the
        # process id on managed TPU environments — forcing 0 would make every
        # host claim rank 0 and wedge the rendezvous.
        process_id = int(env_pid) if env_pid is not None else None
    if coordinator_address is None or (num_processes is not None and num_processes <= 1):
        return False
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def make_hybrid_mesh(feature_parallel: int = 1) -> Mesh:
    """DCN×ICI-aware (data, feature) mesh that works on every topology.

    Layout follows the standard scaling recipe: the data axis spans the
    slowest link (its psums tolerate latency — one small histogram/gradient
    reduction per step), while feature parallelism stays inside a granule so
    its tighter collectives ride ICI.

    The DCN granularity is the number of SLICES, not processes: a
    single-slice multi-host pod is all-ICI (and CPU test meshes report one
    granule), so only a genuinely multi-slice/multi-granule job takes the
    ``create_hybrid_device_mesh`` path — sizing it by ``process_count`` (the
    obvious mistake) breaks both single-slice pods and multi-process CPU
    testing, which is exactly what the 2-process regression test checks.
    """
    devs = jax.devices()
    granules: dict = {}
    for d in devs:
        granules.setdefault(
            getattr(d, "slice_index", d.process_index), []).append(d)
    if len(granules) == 1:
        # one granule: plain global mesh (jax.devices() is process-major, so
        # the data axis still spans hosts in a multi-host single-slice pod)
        return make_mesh(feature_parallel=feature_parallel, devices=devs)
    sizes = {len(v) for v in granules.values()}
    if len(sizes) != 1:
        raise ValueError(f"uneven device granules: {sorted(sizes)}")
    from jax.experimental import mesh_utils

    local = sizes.pop()
    if local % feature_parallel:
        raise ValueError(
            f"{local} per-granule devices not divisible by "
            f"feature_parallel={feature_parallel}")
    grid = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(local // feature_parallel, feature_parallel),
        dcn_mesh_shape=(len(granules), 1),
        # our granule fallback keys by process_index when slice_index is
        # absent; tell mesh_utils the same, or it raises on such platforms
        process_is_granule=not hasattr(devs[0], "slice_index"))
    return Mesh(grid, (DATA_AXIS, FEATURE_AXIS))


def global_batch_from_local(x_local: np.ndarray, mesh: Mesh) -> jax.Array:
    """Per-process rows -> one global row-sharded array.

    Each host feeds only the rows it loaded (e.g. from its own Kafka
    partition assignment); the result behaves as the concatenated global
    batch sharded over the data axis. Local row counts must be equal across
    processes (pad with zero rows + a validity mask as in ``shard_rows``).
    """
    sharding = (batch_sharding(mesh) if x_local.ndim > 1
                else NamedSharding(mesh, P(DATA_AXIS)))
    return jax.make_array_from_process_local_data(sharding, x_local)
