"""Mesh data-parallel serving: one worker drives every local chip.

``ServingPipeline`` already accepts a ``mesh`` and shards row batches over
its "data" axis (models/pipeline.py ``_device_rows``/``_device_packed`` via
``shard_rows``) — jit follows input shardings, so the SAME compiled scoring
programs serve single-chip and mesh placements. What this module adds is
the serving-lane packaging of that placement (docs/fleet.md "Mesh
data-parallel scoring"):

* :class:`MeshServingPipeline` — a drop-in ``ServingPipeline`` whose chunk
  size scales with the chip count (``per_chip_batch`` rows per chip) and
  whose padding-ladder targets stay divisible by the data axis, so every
  compiled shape splits into identical per-chip shards (the ladder's rungs
  become per-chip rungs: a global rung R runs R/dp rows on each chip).
  On ONE device it constructs the plain single-device pipeline — byte-
  identical scoring, no mesh in the way.
* :func:`make_serving_mesh` — all local devices on the data axis (models
  are tiny and replicated; rows are plentiful — the right layout for this
  workload, parallel/mesh.py).

Parity contract: labels and probabilities equal the single-device pipeline
on the same inputs (padding rows are zeros, sliced off at resolve;
per-row scoring has no cross-row collectives) — pinned by
tests/test_fleet.py. ``health()['device']`` carries ``mesh_devices`` and
the ``per_chip_rungs`` prewarm populated.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

from fraud_detection_tpu.models.pipeline import ServingPipeline
from fraud_detection_tpu.parallel.mesh import DATA_AXIS, make_mesh


def make_serving_mesh(n_devices: Optional[int] = None,
                      devices: Optional[Sequence[jax.Device]] = None):
    """All (or the first ``n_devices``) local devices on the data axis."""
    return make_mesh(n_devices=n_devices, devices=devices)


def local_device_count() -> int:
    return jax.local_device_count()


class MeshServingPipeline(ServingPipeline):
    """Data-parallel ``ServingPipeline`` over the local device mesh.

    ``per_chip_batch`` is the chunk size EACH chip scores; the pipeline's
    ``batch_size`` becomes ``per_chip_batch * data_parallel`` so one
    engine micro-batch feeds every chip at single-chip occupancy. With one
    device the constructor degrades to the exact single-device pipeline
    (``mesh=None`` — the fall-back-byte-identically contract)."""

    def __init__(self, featurizer, model, *, per_chip_batch: int = 256,
                 mesh=None, fold_idf: bool = True, int8: bool = False,
                 featurize_device=False,
                 featurize_width=None, featurize_tokens=None):
        if per_chip_batch < 1:
            raise ValueError(
                f"per_chip_batch must be >= 1, got {per_chip_batch}")
        if mesh is None:
            mesh = make_serving_mesh()
        dp = int(dict(mesh.shape).get(DATA_AXIS, 1))
        self.data_parallel = dp
        self.per_chip_batch = per_chip_batch
        # Device-side featurization shards with scoring: the raw-byte
        # staging tensor row-shards over the same data axis (shard_rows in
        # _dispatch_bytes), and _pad_rows below keeps every rung
        # dp-divisible so each chip featurizes rung/dp rows.
        super().__init__(featurizer, model, fold_idf=fold_idf,
                         batch_size=per_chip_batch * dp,
                         mesh=mesh if dp > 1 else None, int8=int8,
                         featurize_device=featurize_device,
                         featurize_width=featurize_width,
                         featurize_tokens=featurize_tokens)
        # The 1-device fallback drops the mesh (exact single-device path)
        # but the health block still says "mesh lane, 1 chip" rather than
        # the plain pipeline's 0 — observers can tell the lane apart.
        self.device_stats.mesh_devices = dp

    def _pad_rows(self, n: int) -> int:
        """Ladder rung for an n-row chunk, rounded up to a data-axis
        multiple: keeps every compiled shape exactly shardable, so
        ``shard_rows`` never appends its own padding rows (which would
        silently fork the compiled-shape menu per chunk size)."""
        target = super()._pad_rows(n)
        dp = self.data_parallel
        return -(-target // dp) * dp if dp > 1 else target

    @classmethod
    def from_pipeline(cls, pipe: ServingPipeline, *,
                      per_chip_batch: Optional[int] = None,
                      mesh=None) -> "MeshServingPipeline":
        """Mesh twin of an existing pipeline (same featurizer + model —
        the bench's parity comparisons build both from one artifact)."""
        dev = pipe._dev_feat
        feat_kwargs = {}
        if dev is not None:
            feat_kwargs = {
                "featurize_device": ("interpret" if dev.spec.interpret
                                     else True),
                "featurize_width": dev.width,
                "featurize_tokens": dev.tokens,
            }
        return cls(pipe.featurizer, pipe.model,
                   per_chip_batch=per_chip_batch or pipe.batch_size,
                   mesh=mesh, int8=pipe.int8, **feat_kwargs)
