"""Model lifecycle subsystem: versioned registry, hot swap, shadow, promotion.

The reference serves one frozen Spark ``PipelineModel`` directory forever
(SURVEY.md L1) — updating the fraud model means stopping the app, and a bad
model is only discovered in production. This package turns the static scorer
into an operable inference system:

  registry.py   filesystem model registry — versioned dirs, atomic publish,
                content-hash verification, poll-based watch, JSONL audit log
  hotswap.py    HotSwapPipeline — RCU-style zero-downtime model swap with
                pre-warming (XLA compile off the hot path)
  shadow.py     ShadowScorer — async candidate scoring with divergence stats
                (agreement, mean |Δp|, flag-rate delta, PSI)
  promote.py    PromotionPolicy + LifecycleController — auto promote/reject
                staged candidates, explicit rollback, audited transitions

See docs/model_lifecycle.md for the full contract.
"""

from fraud_detection_tpu.registry.hotswap import HotSwapPipeline
from fraud_detection_tpu.registry.promote import (LifecycleController,
                                                  PromotionDecision,
                                                  PromotionPolicy)
from fraud_detection_tpu.registry.registry import (ModelRegistry, ModelVersion,
                                                   RegistryError,
                                                   RegistryIntegrityError)
from fraud_detection_tpu.registry.shadow import ShadowScorer

__all__ = ["HotSwapPipeline", "LifecycleController", "ModelRegistry",
           "ModelVersion", "PromotionDecision", "PromotionPolicy",
           "RegistryError", "RegistryIntegrityError", "ShadowScorer"]
