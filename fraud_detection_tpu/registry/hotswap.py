"""Zero-downtime model swapping for the streaming engine.

``HotSwapPipeline`` stands where a ``ServingPipeline`` does — the engine
scores through it untouched — and swaps the pipeline underneath RCU-style:
readers (the engine's dispatch path, any thread) take NO lock; each scoring
call reads the active ``(version, pipeline)`` reference exactly once, so a
batch dispatched concurrently with a swap scores wholly with one model or
wholly with the other, never a mix. Writers (the lifecycle watcher thread)
serialize on a small lock that the hot path never touches.

The swap contract that keeps p99 flat: a candidate is PRE-WARMED before it
becomes active — a representative dummy batch runs through every jitted
program it will serve (text path, and the raw-JSON path when available), so
the XLA compile happens off the hot path, at stage/swap time, not on the
first production batch after the swap.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional, Sequence, Tuple

_PREWARM_TEXTS = [
    "urgent your account has been suspended verify your social security "
    "number immediately to avoid arrest and pay the processing fee now",
    "good morning thank you for calling the clinic i would like to confirm "
    "my appointment for tomorrow afternoon please bring your insurance card",
]


class HotSwapPipeline:
    """A ServingPipeline holder whose model can be replaced between batches.

    Engine-facing surface: ``predict_async`` / ``predict_json_async`` (the
    two calls the streaming engine makes) plus ``predict``/``predict_one``
    and attribute delegation for everything else — drop-in wherever a
    ``ServingPipeline`` is accepted.
    """

    def __init__(self, pipeline, version: Optional[int] = None, *,
                 prewarm_texts: Optional[Sequence[str]] = None,
                 prewarm_buckets: Optional[Sequence[int]] = None,
                 clock=time.monotonic):
        # Single-reference RCU publish point: one tuple, swapped atomically
        # under the GIL; every reader dereferences it exactly once per call.
        self._active: Tuple[Optional[int], object] = (version, pipeline)
        self._staged: Optional[Tuple[Optional[int], object]] = None
        self._lock = threading.Lock()   # writers only; readers never touch it
        self._clock = clock
        self._prewarm_texts = list(prewarm_texts or _PREWARM_TEXTS)
        # Scheduler padding-bucket ladder (sched/batcher.py): once
        # configured, EVERY candidate is pre-warmed at every rung, so
        # neither a swap nor a first small batch compiles on the hot path.
        self._pad_buckets: Optional[Tuple[int, ...]] = None
        self._ladder_costs: Optional[dict] = None  # measured once, reused
        self.swaps = 0
        self._last_swap_at: Optional[float] = None
        if prewarm_buckets is not None:
            self.configure_ladder(prewarm_buckets, prewarm=False)

    # ------------------------------------------------------------------
    # reader surface (lock-free)
    # ------------------------------------------------------------------

    def predict_async(self, texts):
        return self._active[1].predict_async(texts)

    def predict_json_async(self, values, text_field: str = "text"):
        return self._active[1].predict_json_async(values, text_field)

    def predict(self, texts):
        return self._active[1].predict(texts)

    def predict_one(self, text: str):
        return self._active[1].predict_one(text)

    @property
    def batch_size(self) -> int:
        return self._active[1].batch_size

    @property
    def active_version(self) -> Optional[int]:
        return self._active[0]

    @property
    def active_pipeline(self):
        return self._active[1]

    @property
    def staged_version(self) -> Optional[int]:
        staged = self._staged
        return staged[0] if staged is not None else None

    @property
    def staged_pipeline(self):
        staged = self._staged
        return staged[1] if staged is not None else None

    def __getattr__(self, name):
        # Anything beyond the scoring surface (featurizer, model, mesh…)
        # reads from the CURRENT active pipeline.
        return getattr(self._active[1], name)

    # ------------------------------------------------------------------
    # writer surface (lifecycle thread)
    # ------------------------------------------------------------------

    def configure_ladder(self, buckets: Sequence[int], *,
                         prewarm: bool = True,
                         costs: Optional[dict] = None) -> None:
        """Adopt a scheduler padding-bucket ladder (sched/batcher.py): the
        active pipeline (and any staged candidate) starts padding partial
        batches to ladder rungs, and every future ``prewarm`` — i.e. every
        swap/stage candidate — compiles every rung, keeping the hot path
        compile-free across swaps AND across batch sizes.

        ``costs`` caches the measured per-rung device costs the geometry
        came from (``measure_ladder`` / sched measure_rung_costs): swap and
        stage candidates then only COMPILE the selected rungs — the cost
        curve is a property of the rung shapes, not the weights, so
        candidates never re-bench."""
        # Writer-side lock (flightcheck FC102): configure_ladder runs on
        # the scheduler's driver thread while swap/stage read _pad_buckets
        # on the lifecycle watcher — the lock keeps the buckets+costs pair
        # a single consistent publish. The prewarm calls below stay OUTSIDE
        # it: they compile for seconds and readers must not block.
        with self._lock:
            self._pad_buckets = tuple(sorted(set(int(b) for b in buckets)))
            if costs is not None:
                self._ladder_costs = dict(costs)
        for target in (self.active_pipeline, self.staged_pipeline):
            if target is not None:
                if prewarm:
                    self.prewarm(target)
                else:
                    target.pad_ladder = self._pad_buckets

    def measure_ladder(self, candidates: Sequence[int], *,
                       texts: Optional[Sequence[str]] = None,
                       repeats: int = 3) -> dict:
        """Time candidate rungs on the ACTIVE pipeline (compile excluded —
        sched/batcher.py measure_rung_costs) and cache the table; the
        scheduler's cost-aware prewarm calls this instead of re-measuring
        per swap. The active pipeline is left padded to the candidate set
        until ``configure_ladder`` applies the selected geometry."""
        from fraud_detection_tpu.sched.batcher import measure_rung_costs

        costs = measure_rung_costs(self.active_pipeline, tuple(candidates),
                                   texts=list(texts or self._prewarm_texts),
                                   repeats=repeats)
        with self._lock:   # writer-side publish, same contract as configure
            self._ladder_costs = dict(costs)
        return costs

    @property
    def ladder_costs(self) -> Optional[dict]:
        """Measured per-rung cost table (seconds/batch) the current ladder
        was derived from; None before any measurement."""
        return self._ladder_costs

    @property
    def pad_buckets(self) -> Optional[Tuple[int, ...]]:
        return self._pad_buckets

    def prewarm(self, pipeline) -> None:
        """Run a representative dummy batch through every jitted program the
        pipeline will serve, so compiles happen HERE, not on the first
        post-swap production batch. Blocks until device results land. With a
        ladder configured, every rung's shape is warmed (a partial batch
        then pads to a rung, so the rung set IS the compiled-shape menu).

        Also RE-PINS the candidate's model arrays HBM-resident
        (ServingPipeline.pin_device): pinning happens once per model
        version, here at stage/swap time — never per batch — so a hot swap
        pays its uploads off the hot path like its compiles."""
        pin = getattr(pipeline, "pin_device", None)
        if callable(pin):
            pin()
        if self._pad_buckets is not None:
            from fraud_detection_tpu.sched.batcher import prewarm_ladder

            prewarm_ladder(pipeline, self._pad_buckets,
                           texts=self._prewarm_texts)
            return
        n = max(int(getattr(pipeline, "batch_size", 1)), 1)
        texts = [self._prewarm_texts[i % len(self._prewarm_texts)]
                 for i in range(min(n, 256))]
        pipeline.predict(texts)
        # The raw-JSON fast path compiles a separate program; warm it when
        # the featurizer supports it (mirrors the engine's own probe).
        values = [json.dumps({"text": t}).encode() for t in texts]
        fast = pipeline.predict_json_async(values)
        if fast is not None:
            fast[0].resolve()

    def swap(self, pipeline, version: Optional[int] = None, *,
             prewarm: bool = True) -> Optional[int]:
        """Make ``pipeline`` active (pre-warming it first, off the hot
        path); returns the version it replaced. Readers mid-batch keep the
        old model for that batch — nothing blocks, nothing tears."""
        if prewarm:
            self.prewarm(pipeline)
        elif self._pad_buckets is not None:
            pipeline.pad_ladder = self._pad_buckets  # ladder survives swaps
        with self._lock:
            old_version = self._active[0]
            self._active = (version, pipeline)
            self.swaps += 1
            self._last_swap_at = self._clock()
        return old_version

    def stage(self, pipeline, version: Optional[int] = None, *,
              prewarm: bool = True) -> None:
        """Hold a candidate next to the active model (shadow scoring reads
        it; ``promote_staged`` makes it active). Pre-warms at stage time so
        promotion itself is instant."""
        if prewarm:
            self.prewarm(pipeline)
        elif self._pad_buckets is not None:
            pipeline.pad_ladder = self._pad_buckets  # ladder survives swaps
        with self._lock:
            self._staged = (version, pipeline)

    def promote_staged(self) -> Optional[int]:
        """Swap the staged candidate in; returns its version. The candidate
        was pre-warmed at stage time, so this is a pure pointer swap."""
        with self._lock:
            if self._staged is None:
                raise RuntimeError("no staged candidate to promote")
            version, pipeline = self._staged
            self._staged = None
            self._active = (version, pipeline)
            self.swaps += 1
            self._last_swap_at = self._clock()
        return version

    def discard_staged(self) -> Optional[int]:
        with self._lock:
            staged, self._staged = self._staged, None
        return staged[0] if staged is not None else None

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def lifecycle_snapshot(self) -> dict:
        """The ``model`` block of ``StreamingClassifier.health()`` (minus
        the shadow stats, which the engine merges in from its scorer)."""
        now = self._clock()
        return {
            "active_version": self.active_version,
            "staged_version": self.staged_version,
            "swaps": self.swaps,
            "last_swap_age_sec": (None if self._last_swap_at is None
                                  else now - self._last_swap_at),
        }
