"""Promotion policy + lifecycle controller: the decision layer.

``PromotionPolicy`` turns a shadow divergence snapshot plus the engine's
health into one of three actions: WAIT (not enough evidence, or the engine
is currently unhealthy — never promote into an incident), PROMOTE (the
candidate is statistically equivalent where it must be), or REJECT (it
diverges beyond the configured bounds).

``LifecycleController`` owns the end-to-end flow the serve CLI drives:
poll the registry for new versions (``--watch``), verify + load + pre-warm
each, either swap directly or stage for shadow evaluation (``--shadow``),
apply the policy each tick (``--promote-policy``), and support explicit
``rollback()`` to any prior version. EVERY transition — stage, promote,
reject, rollback, integrity failure — is an append-only JSONL audit event
in the registry (``audit.jsonl``), so the model history is reconstructible
from the registry directory alone.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, List, Optional

from fraud_detection_tpu.registry.registry import (ModelRegistry,
                                                   RegistryError,
                                                   RegistryIntegrityError)
from fraud_detection_tpu.utils import get_logger
from fraud_detection_tpu.utils.racecheck import ExclusiveRegion

log = get_logger("registry.promote")


@dataclass(frozen=True)
class PromotionDecision:
    action: str                  # "wait" | "promote" | "reject"
    reasons: tuple = ()

    def __str__(self) -> str:
        return f"{self.action} ({'; '.join(self.reasons) or 'ok'})"


@dataclass
class PromotionPolicy:
    """Thresholds for auto-promotion of a shadow-scored candidate.

    The defaults are conservative for a binary fraud scorer: at least
    ``min_shadow_batches`` micro-batches and ``min_shadow_rows`` rows of
    evidence; label disagreement above ``max_disagreement`` or a score-
    distribution PSI above ``max_psi`` (0.25 = "shifted" by the usual rule
    of thumb) or a flag-rate swing above ``max_flag_rate_delta`` rejects;
    an unhealthy engine (flush failures in progress) defers the decision —
    promotion must never ride an incident."""

    min_shadow_batches: int = 5
    min_shadow_rows: int = 100
    max_disagreement: float = 0.02
    max_psi: float = 0.25
    max_flag_rate_delta: float = 0.10
    require_healthy: bool = True

    @classmethod
    def parse(cls, spec: str) -> "PromotionPolicy":
        """Build from a CLI spec like
        ``min_batches=5,max_disagreement=0.02,max_psi=0.25``. Unknown keys
        are an error (a typo must not silently loosen a threshold)."""
        aliases = {"min_batches": "min_shadow_batches",
                   "min_rows": "min_shadow_rows"}
        kwargs = {}
        for part in filter(None, (p.strip() for p in spec.split(","))):
            key, sep, value = part.partition("=")
            if not sep:
                raise ValueError(f"bad policy term {part!r} (want key=value)")
            key = aliases.get(key, key)
            fields = cls.__dataclass_fields__
            if key not in fields:
                raise ValueError(
                    f"unknown policy key {part.split('=')[0]!r} "
                    f"(known: {sorted(set(fields) | set(aliases))})")
            typ = fields[key].type
            if typ == "bool" or typ is bool:
                kwargs[key] = value.lower() in ("1", "true", "yes")
            elif typ == "int" or typ is int:
                kwargs[key] = int(value)
            else:
                kwargs[key] = float(value)
        return cls(**kwargs)

    def evaluate(self, shadow: dict,
                 health: Optional[dict] = None) -> PromotionDecision:
        """Decide on a candidate given its shadow snapshot + engine health."""
        if self.require_healthy and health is not None:
            if health.get("consecutive_flush_failures", 0) > 0:
                return PromotionDecision(
                    "wait", ("engine unhealthy: producer flush failing",))
        if (shadow.get("batches", 0) < self.min_shadow_batches
                or shadow.get("rows", 0) < self.min_shadow_rows):
            return PromotionDecision(
                "wait", (f"insufficient shadow evidence: "
                         f"{shadow.get('batches', 0)} batches / "
                         f"{shadow.get('rows', 0)} rows "
                         f"(need {self.min_shadow_batches} / "
                         f"{self.min_shadow_rows})",))
        reasons = []
        agreement = shadow.get("agreement_rate")
        if agreement is not None and 1.0 - agreement > self.max_disagreement:
            reasons.append(f"disagreement {1.0 - agreement:.4f} > "
                           f"max {self.max_disagreement}")
        psi = shadow.get("psi")
        if psi is not None and psi > self.max_psi:
            reasons.append(f"score-distribution PSI {psi:.4f} > "
                           f"max {self.max_psi}")
        delta = shadow.get("flag_rate_delta")
        if delta is not None and abs(delta) > self.max_flag_rate_delta:
            reasons.append(f"flag-rate delta {delta:+.4f} beyond "
                           f"±{self.max_flag_rate_delta}")
        if reasons:
            return PromotionDecision("reject", tuple(reasons))
        return PromotionDecision(
            "promote", (f"agreement {agreement:.4f}, PSI "
                        f"{psi if psi is not None else 0.0:.4f} over "
                        f"{shadow['rows']} rows",))


def _public(snapshot: dict) -> dict:
    """Shadow snapshot without the bulky histograms (audit-log friendly)."""
    return {k: v for k, v in snapshot.items()
            if not k.startswith("score_hist")}


class LifecycleController:
    """Drives a ``HotSwapPipeline`` from a ``ModelRegistry``.

    ``tick()`` is one poll step, safe to call from any single thread (the
    serve CLI runs it on a watcher thread; tests call it inline for
    determinism). With a ``shadow`` scorer, new versions are STAGED and a
    ``policy`` decides promotion; without one, new versions swap in
    directly (still pre-warmed). All loads are hash-verified; a corrupted
    publish is audited + skipped, never served."""

    def __init__(self, registry: ModelRegistry, hotswap, *,
                 shadow=None, policy: Optional[PromotionPolicy] = None,
                 batch_size: int = 256, mesh=None,
                 health_fn: Optional[Callable[[], Optional[dict]]] = None,
                 on_transition: Optional[Callable[[dict], None]] = None):
        self.registry = registry
        self.hotswap = hotswap
        self.shadow = shadow
        self.policy = policy
        self.batch_size = batch_size
        self.mesh = mesh
        self.health_fn = health_fn
        # Observer hook: called with EVERY audit record this controller
        # emits (stage/promote/reject/rollback/load_failed), synchronously
        # on the transitioning thread — the learn loop (learn/loop.py)
        # tracks its candidates' fates through this. Must be fast and
        # non-reentrant (it runs inside the watch region); exceptions are
        # swallowed with a log line — an observer must never veto or kill
        # a lifecycle transition.
        self.on_transition = on_transition
        # Cursor: adopt everything NEWER than the active version (a version
        # published before the watcher started must still be picked up).
        # Seeding from latest() instead would silently skip it.
        active = getattr(hotswap, "active_version", None)
        if active is None:
            latest = registry.latest()
            active = latest.version if latest is not None else 0
        self._seen = active
        self.events: List[dict] = []    # every audited transition, in order
        # Race tripwire (utils/racecheck.py): tick() is documented "safe to
        # call from any SINGLE thread", and rollback() is the operator
        # overruling the watcher — the two deciding concurrently could
        # promote a candidate the rollback just discarded. The region makes
        # that collision a loud RaceError (the watcher loop logs it and
        # retries next interval) instead of a silent double transition.
        self._region = ExclusiveRegion("LifecycleController.watch")

    def _audit(self, event: str, **fields) -> dict:
        record = self.registry.audit(event, **fields)
        self.events.append(record)
        if self.on_transition is not None:
            try:
                self.on_transition(record)
            except Exception as e:  # noqa: BLE001 — observers never veto
                log.warning("lifecycle on_transition observer failed: %s", e)
        return record

    def tick(self) -> List[dict]:
        """One poll step: adopt new versions, evaluate a staged candidate.
        Returns the audit events this tick generated."""
        with self._region:
            return self._tick_locked()

    def _tick_locked(self) -> List[dict]:
        before = len(self.events)
        for mv in self.registry.poll_new(self._seen):
            self._seen = mv.version
            try:
                mv, pipe = self.registry.load(mv.version,
                                              batch_size=self.batch_size,
                                              mesh=self.mesh)
            except (RegistryIntegrityError, RegistryError, ValueError,
                    OSError, KeyError) as e:
                self._audit("load_failed", version=mv.version, error=str(e))
                log.warning("registry v%04d failed verification/load: %s",
                            mv.version, e)
                continue
            if self.shadow is not None:
                replaced = self.hotswap.staged_version
                self.hotswap.stage(pipe, mv.version)   # pre-warms
                self.shadow.set_candidate(pipe, mv.version)
                self._audit("stage", version=mv.version, replaced=replaced)
            else:
                old = self.hotswap.swap(pipe, mv.version)  # pre-warms
                self._audit("promote", version=mv.version, previous=old,
                            mode="direct")
        if (self.shadow is not None and self.policy is not None
                and self.hotswap.staged_version is not None):
            snapshot = self.shadow.snapshot()
            health = self.health_fn() if self.health_fn is not None else None
            decision = self.policy.evaluate(snapshot, health)
            if decision.action == "promote":
                version = self.hotswap.promote_staged()
                self.shadow.clear_candidate()
                self._audit("promote", version=version, mode="shadow",
                            reasons=list(decision.reasons),
                            shadow=_public(snapshot))
            elif decision.action == "reject":
                version = self.hotswap.discard_staged()
                self.shadow.clear_candidate()
                self._audit("reject", version=version,
                            reasons=list(decision.reasons),
                            shadow=_public(snapshot))
        return self.events[before:]

    def rollback(self, version: int) -> dict:
        """Swap any prior published version back in (verified, pre-warmed).
        A staged candidate, if any, is discarded — rolling back IS the
        operator overruling the pipeline. Shares tick()'s exclusive region:
        a rollback racing a concurrent tick raises RaceError rather than
        letting the two transition the same staged candidate twice (stop
        the watcher, or accept the retry, before rolling back)."""
        with self._region:
            mv, pipe = self.registry.load(version, batch_size=self.batch_size,
                                          mesh=self.mesh)
            discarded = self.hotswap.discard_staged()
            if self.shadow is not None:
                self.shadow.clear_candidate()
            old = self.hotswap.swap(pipe, mv.version)
            return self._audit("rollback", version=mv.version, previous=old,
                               discarded_staged=discarded)

    def run_in_thread(self, interval: float = 2.0,
                      stop: Optional[threading.Event] = None):
        """Spawn the watcher thread (daemon). Returns (thread, stop_event);
        set the event and join to stop. tick() errors are logged, never
        fatal — a broken registry scan must not take serving down."""
        stop = stop or threading.Event()

        def loop():
            while not stop.is_set():
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — watcher must survive
                    log.warning("lifecycle tick failed: %s", e)
                stop.wait(interval)

        thread = threading.Thread(target=loop, daemon=True,
                                  name="lifecycle-watcher")
        thread.start()
        return thread, stop
