"""Filesystem model registry: versioned, atomic, verified, watchable.

Layout (one directory per published version, never mutated after publish):

    <root>/v0001/manifest.json        registry manifest (below)
    <root>/v0001/checkpoint/          a native checkpoint (checkpoint/native.py)
    <root>/audit.jsonl                append-only lifecycle event log

The version manifest carries the registry schema version, creation time,
a SHA-256 content hash of every checkpoint file, the parent version this
model was trained to replace, and the training metrics the publisher chose
to attach. ``load()`` re-hashes every file against the manifest before a
single byte reaches the model loader — a corrupted or truncated checkpoint
fails loudly with the offending filename instead of scoring garbage.

Publish is ATOMIC: the whole version directory is assembled under a hidden
``.publish-*`` temp dir in the same filesystem and enters the namespace via
one ``os.replace`` to ``vNNNN``. A crash mid-publish leaves only a hidden
temp dir that every listing skips; readers can never observe a torn
version. Concurrent publishers race on the version number — the loser's
rename fails (the directory exists and is non-empty) and retries with the
next number, so both publishes land, ordered.

``watch()`` is poll-based (no inotify dependency): the root directory's
mtime changes whenever a rename lands a new version, so the cheap pre-check
is one ``stat``; only then is the directory re-listed and filtered to
versions whose manifest is present (i.e. fully published).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import tempfile
import time
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

SCHEMA_VERSION = 1
MANIFEST_NAME = "manifest.json"
CHECKPOINT_SUBDIR = "checkpoint"
AUDIT_LOG = "audit.jsonl"
_VERSION_RE = re.compile(r"^v(\d{4,})$")
_HASH_CHUNK = 1 << 20


class RegistryError(RuntimeError):
    """Registry misuse or unreadable state (empty registry, unknown version)."""


class RegistryIntegrityError(RegistryError):
    """A version's on-disk bytes do not match its manifest hashes — the
    checkpoint is corrupted/truncated and must not be loaded."""


@dataclass(frozen=True)
class ModelVersion:
    """One published version: its number, directory, and parsed manifest."""

    version: int
    path: str
    manifest: dict

    @property
    def checkpoint_path(self) -> str:
        return os.path.join(self.path, CHECKPOINT_SUBDIR)

    @property
    def name(self) -> str:
        return f"v{self.version:04d}"


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(_HASH_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    """Durably record directory entries (best-effort on non-POSIX)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class ModelRegistry:
    """Versioned model store rooted at one directory (see module docstring)."""

    def __init__(self, root: str, clock=time.time):
        self.root = root
        self._clock = clock
        os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------------
    # listing / reading
    # ------------------------------------------------------------------

    def list_versions(self) -> List[int]:
        """Published version numbers, ascending. A directory counts only if
        its manifest exists — publish is atomic, so this also filters any
        hand-made partial dirs (they are torn publishes by definition)."""
        out = []
        try:
            entries = os.listdir(self.root)
        except FileNotFoundError:
            return out
        for name in entries:
            m = _VERSION_RE.match(name)
            if m and os.path.isfile(os.path.join(self.root, name, MANIFEST_NAME)):
                out.append(int(m.group(1)))
        out.sort()
        return out

    def get(self, version: int) -> ModelVersion:
        path = os.path.join(self.root, f"v{version:04d}")
        manifest_path = os.path.join(path, MANIFEST_NAME)
        try:
            with open(manifest_path) as fh:
                manifest = json.load(fh)
        except FileNotFoundError:
            raise RegistryError(
                f"registry {self.root}: version v{version:04d} does not exist "
                f"(published: {self.list_versions() or 'none'})")
        except ValueError as e:
            raise RegistryIntegrityError(
                f"registry {self.root}: v{version:04d}/{MANIFEST_NAME} is not "
                f"valid JSON ({e}) — torn or corrupted manifest")
        return ModelVersion(version, path, manifest)

    def latest(self) -> Optional[ModelVersion]:
        versions = self.list_versions()
        return self.get(versions[-1]) if versions else None

    # ------------------------------------------------------------------
    # publishing
    # ------------------------------------------------------------------

    def publish(self, featurizer, model, *, metrics: Optional[dict] = None,
                parent: Optional[int] = None,
                extra: Optional[dict] = None) -> ModelVersion:
        """Save ``featurizer`` + ``model`` as the next version (atomic).

        ``parent`` defaults to the current latest version — the lineage
        field promotion/rollback audits refer to. ``metrics`` is the
        publisher's training/eval summary, carried verbatim in the manifest
        (and shown in audit events / eval reports)."""
        from fraud_detection_tpu.checkpoint.native import save_checkpoint

        def write(ckpt_dir: str) -> None:
            save_checkpoint(ckpt_dir, featurizer, model)

        return self._publish_with(write, metrics=metrics, parent=parent,
                                  extra=extra)

    def publish_dir(self, checkpoint_dir: str, *,
                    metrics: Optional[dict] = None,
                    parent: Optional[int] = None,
                    extra: Optional[dict] = None) -> ModelVersion:
        """Publish an existing native checkpoint directory (copied in)."""
        if not os.path.isfile(os.path.join(checkpoint_dir, "manifest.json")):
            raise RegistryError(
                f"{checkpoint_dir} is not a native checkpoint directory "
                "(no manifest.json)")

        def write(ckpt_dir: str) -> None:
            shutil.copytree(checkpoint_dir, ckpt_dir, dirs_exist_ok=True)

        return self._publish_with(write, metrics=metrics, parent=parent,
                                  extra=extra)

    def _publish_with(self, write_checkpoint, *, metrics, parent,
                      extra) -> ModelVersion:
        if parent is None:
            prior = self.latest()
            parent = prior.version if prior is not None else None
        tmp = tempfile.mkdtemp(prefix=".publish-", dir=self.root)
        try:
            ckpt_dir = os.path.join(tmp, CHECKPOINT_SUBDIR)
            os.makedirs(ckpt_dir, exist_ok=True)
            write_checkpoint(ckpt_dir)
            files = {}
            for dirpath, _, names in os.walk(ckpt_dir):
                for name in sorted(names):
                    full = os.path.join(dirpath, name)
                    rel = os.path.relpath(full, tmp)
                    files[rel] = {"sha256": _sha256_file(full),
                                  "bytes": os.path.getsize(full)}
            ckpt_meta_path = os.path.join(ckpt_dir, "manifest.json")
            with open(ckpt_meta_path) as fh:
                model_kind = json.load(fh).get("model_kind")
            manifest = {
                "schema_version": SCHEMA_VERSION,
                "created_at": self._clock(),
                "model_kind": model_kind,
                "files": files,
                "metrics": metrics,
                "parent": parent,
            }
            if extra:
                manifest.update(extra)
            manifest_tmp = os.path.join(tmp, MANIFEST_NAME)
            with open(manifest_tmp, "w") as fh:
                json.dump(manifest, fh, indent=2)
                fh.flush()
                os.fsync(fh.fileno())
            # Allocate the version number LAST and enter the namespace with
            # one rename. A concurrent publisher that wins the same number
            # makes this replace fail (existing non-empty dir) — retry with
            # the next number; both publishes land.
            versions = self.list_versions()
            n = (versions[-1] if versions else 0) + 1
            while True:
                target = os.path.join(self.root, f"v{n:04d}")
                try:
                    os.replace(tmp, target)
                    break
                except OSError:
                    if not os.path.exists(target):
                        raise      # not a version-number race: surface it
                    n += 1
            _fsync_dir(self.root)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        mv = ModelVersion(n, target, manifest)
        self.audit("publish", version=n, parent=parent,
                   model_kind=model_kind, metrics=metrics)
        return mv

    # ------------------------------------------------------------------
    # verification / loading
    # ------------------------------------------------------------------

    def verify(self, version: int) -> ModelVersion:
        """Re-hash every checkpoint file against the manifest; raises
        ``RegistryIntegrityError`` naming the first offending file."""
        mv = self.get(version)
        files = mv.manifest.get("files")
        if not isinstance(files, dict) or not files:
            raise RegistryIntegrityError(
                f"{mv.name}: manifest carries no file hashes "
                "(schema_version "
                f"{mv.manifest.get('schema_version')!r}) — cannot verify")
        for rel, meta in files.items():
            full = os.path.join(mv.path, rel)
            if not os.path.isfile(full):
                raise RegistryIntegrityError(
                    f"{mv.name}: checkpoint file {rel!r} is missing — "
                    "torn or tampered version directory")
            size = os.path.getsize(full)
            if size != meta["bytes"]:
                raise RegistryIntegrityError(
                    f"{mv.name}: {rel!r} is {size} bytes, manifest says "
                    f"{meta['bytes']} — truncated or corrupted checkpoint")
            digest = _sha256_file(full)
            if digest != meta["sha256"]:
                raise RegistryIntegrityError(
                    f"{mv.name}: {rel!r} content hash mismatch "
                    f"(sha256 {digest[:12]}… != manifest "
                    f"{meta['sha256'][:12]}…) — corrupted checkpoint; "
                    "refusing to load")
        return mv

    def load(self, version: Optional[int] = None, *, batch_size: int = 256,
             mesh=None) -> Tuple[ModelVersion, "object"]:
        """Verify + load a version (default: latest) as a ServingPipeline."""
        from fraud_detection_tpu.models.pipeline import ServingPipeline

        if version is None:
            latest = self.latest()
            if latest is None:
                raise RegistryError(
                    f"registry {self.root} has no published versions")
            version = latest.version
        mv = self.verify(version)
        pipe = ServingPipeline.from_checkpoint(
            mv.checkpoint_path, batch_size=batch_size, mesh=mesh)
        return mv, pipe

    # ------------------------------------------------------------------
    # watching
    # ------------------------------------------------------------------

    def poll_new(self, after: int) -> List[ModelVersion]:
        """All fully-published versions > ``after``, ascending."""
        return [self.get(v) for v in self.list_versions() if v > after]

    def _root_mtime(self) -> int:
        try:
            return os.stat(self.root).st_mtime_ns
        except OSError:
            return -1

    def watch(self, interval: float = 2.0, *, after: Optional[int] = None,
              stop=None, sleep=time.sleep) -> Iterator[ModelVersion]:
        """Yield new versions as they are published (poll-based).

        One ``stat`` of the root per tick; the directory is re-listed only
        when its mtime moved (a publish's rename always moves it). Versions
        are yielded in order and exactly once; ``after`` seeds the cursor
        (default: current latest). ``stop`` is an optional
        ``threading.Event``-like object ending the generator."""
        if after is None:
            latest = self.latest()
            after = latest.version if latest is not None else 0
        last_mtime = -2  # != any real value: always scan once on entry
        while stop is None or not stop.is_set():
            mtime = self._root_mtime()
            if mtime != last_mtime:
                last_mtime = mtime
                for mv in self.poll_new(after):
                    after = mv.version
                    yield mv
            if stop is not None and stop.wait(interval):
                return
            if stop is None:
                sleep(interval)

    # ------------------------------------------------------------------
    # audit log
    # ------------------------------------------------------------------

    def audit(self, event: str, **fields) -> dict:
        """Append one lifecycle event to ``audit.jsonl`` (single line write,
        flushed + fsynced — the log is the promotion/rollback evidence)."""
        record = {"ts": self._clock(), "event": event, **fields}
        line = json.dumps(record, sort_keys=True)
        with open(os.path.join(self.root, AUDIT_LOG), "a") as fh:
            fh.write(line + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        return record

    def read_audit(self) -> List[dict]:
        path = os.path.join(self.root, AUDIT_LOG)
        if not os.path.isfile(path):
            return []
        out = []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out
