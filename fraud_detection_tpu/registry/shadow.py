"""Shadow scoring: run a candidate model beside the primary, off the hot path.

While a candidate is staged, the engine hands each scored micro-batch's
inputs + primary results to a ``ShadowScorer``. A background worker rescales
the batch with the CANDIDATE and accumulates divergence statistics:

  * agreement rate — fraction of rows where the labels match
  * mean |Δp| — mean absolute probability difference
  * flag-rate delta — candidate flag rate minus primary flag rate
  * PSI — population stability index over the score distribution, from
    per-bin score histograms accumulated on device via the same histogram
    machinery the tree trainer uses (``ops/histogram.histogram_reference``)

The primary path NEVER blocks on the shadow: submission is a non-blocking
put into a bounded queue — under overload (a slow candidate, the steady
state for a bigger model) batches are dropped and counted, so the sampling
rate is a recorded fact, exactly like the async annotation lane
(stream/annotations.py). A raising candidate increments an error counter
and the stream never notices.
"""

from __future__ import annotations

import json
import queue
import random
import threading
import time
from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fraud_detection_tpu.ops.histogram import histogram_reference
from fraud_detection_tpu.utils import get_logger
from fraud_detection_tpu.utils.racecheck import ExclusiveRegion

log = get_logger("registry.shadow")

N_BINS = 20
_PSI_EPS = 1e-4


@partial(jax.jit, static_argnames=("n_bins",))
def _score_hist_device(probs, n_bins: int = N_BINS):
    """(N,) scores in [0, 1] -> (n_bins,) counts, one device program —
    reuses the tree trainer's histogram formulation (n_nodes=1, F=1, K=1)."""
    bins = jnp.clip((probs * n_bins).astype(jnp.int32), 0, n_bins - 1)
    local = jnp.zeros(probs.shape[0], jnp.int32)
    stats = jnp.ones((probs.shape[0], 1), jnp.float32)
    return histogram_reference(bins[:, None], local, stats,
                               n_nodes=1, n_bins=n_bins)[0, 0, :, 0]


def score_histogram(probs: np.ndarray, n_bins: int = N_BINS) -> np.ndarray:
    if probs.size == 0:
        return np.zeros(n_bins, np.float64)
    return np.asarray(_score_hist_device(np.asarray(probs, np.float32),
                                         n_bins=n_bins), np.float64)


def population_stability_index(expected: np.ndarray,
                               observed: np.ndarray) -> float:
    """PSI between two count histograms (smoothed; 0 = identical shape).
    Rule of thumb: < 0.1 stable, 0.1–0.25 drifting, > 0.25 shifted."""
    e = np.asarray(expected, np.float64)
    o = np.asarray(observed, np.float64)
    if e.sum() <= 0 or o.sum() <= 0:
        return 0.0
    p = e / e.sum() + _PSI_EPS
    q = o / o.sum() + _PSI_EPS
    p /= p.sum()
    q /= q.sum()
    return float(np.sum((q - p) * np.log(q / p)))


class ShadowScorer:
    """Bounded-queue async candidate scorer with divergence accounting.

    One instance lives for the whole serve run (shared across workers — all
    methods are thread-safe); candidates come and go via
    ``set_candidate``/``clear_candidate``, each reset starting a fresh
    stats window. The engine calls ``wants()`` (cheap gate: candidate
    present + sampling draw) then ``submit()`` per micro-batch.
    """

    def __init__(self, *, max_queue: int = 8, sample: float = 1.0,
                 n_bins: int = N_BINS, window_batches: int = 64,
                 clock=time.monotonic,
                 rng: Optional[random.Random] = None):
        if not 0.0 < sample <= 1.0:
            raise ValueError(f"sample must be in (0, 1], got {sample}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if window_batches < 1:
            raise ValueError(
                f"window_batches must be >= 1, got {window_batches}")
        self.sample = sample
        self.n_bins = n_bins
        # Windowed divergence (docs/online_learning.md): per-batch stat
        # tuples for the most recent ``window_batches`` scored batches, so
        # a long-running shadow exposes RECENT agreement/PSI beside the
        # cumulative ones — early agreement must not mask late drift
        # (pinned in tests/test_learn.py).
        self.window_batches = window_batches
        self._rng = rng if rng is not None else random.Random()
        self._clock = clock
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._lock = threading.Lock()
        self._candidate = None          # (version, pipeline) — RCU-read
        self._stop = threading.Event()
        self._reset_stats_locked()
        # Race tripwire (utils/racecheck.py): scoring is single-worker by
        # construction — ONE thread started here, never respawned. The
        # region turns a second concurrent scorer (a future refactor
        # spawning a pool, or an external caller driving _score_item) into
        # an immediate RaceError instead of silently double-counted stats.
        self._region = ExclusiveRegion("ShadowScorer.worker")
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="shadow-scorer")
        self._thread.start()

    def _reset_stats_locked(self) -> None:
        self._batches = 0
        self._rows = 0
        self._agree = 0
        self._abs_dp_sum = 0.0
        self._primary_flagged = 0
        self._candidate_flagged = 0
        self._dropped = 0
        self._errors = 0
        self._sampled_out = 0
        self._primary_hist = np.zeros(self.n_bins, np.float64)
        self._candidate_hist = np.zeros(self.n_bins, np.float64)
        # Recent-window ring: (rows, agree, |dp| sum, p_hist, c_hist) per
        # scored batch, newest last (deque.maxlen drops the oldest).
        from collections import deque

        self._window = deque(maxlen=self.window_batches)
        self._started_at = self._clock()

    # ------------------------------------------------------------------
    # candidate lifecycle
    # ------------------------------------------------------------------

    def set_candidate(self, pipeline, version: Optional[int] = None) -> None:
        with self._lock:
            self._candidate = (version, pipeline)
            self._reset_stats_locked()

    def clear_candidate(self) -> None:
        with self._lock:
            self._candidate = None

    @property
    def candidate_version(self) -> Optional[int]:
        cand = self._candidate
        return cand[0] if cand is not None else None

    @property
    def active(self) -> bool:
        return self._candidate is not None

    # ------------------------------------------------------------------
    # hot-path surface (engine side)
    # ------------------------------------------------------------------

    def wants(self) -> bool:
        """Cheap per-batch gate: candidate staged and sampling draw taken.
        Sampled-out batches are counted so the shadow coverage is known."""
        if self._candidate is None:
            return False
        if self.sample >= 1.0 or self._rng.random() < self.sample:
            return True
        with self._lock:
            self._sampled_out += 1
        return False

    def submit(self, payloads: Sequence, labels, probs, *, raw: bool,
               text_field: str = "text") -> bool:
        """Queue one scored micro-batch for candidate comparison.

        ``payloads`` are raw message bytes (``raw=True``; decoded by the
        worker, off the hot path) or already-decoded texts; ``labels`` /
        ``probs`` are the PRIMARY model's outputs, positionally aligned with
        ``payloads``. NEVER blocks: a full queue drops the batch and counts
        it. Returns whether the batch was enqueued."""
        cand = self._candidate
        if cand is None:
            return False
        try:
            self._queue.put_nowait(
                (cand, payloads, labels, probs, raw, text_field))
            return True
        except queue.Full:
            with self._lock:
                self._dropped += 1
            return False

    def submit_encoded(self, ids, counts, labels, probs) -> bool:
        """Queue a batch of ALREADY-ENCODED rows ((B, L) hashed ids + term
        counts — the learn window's retained form, learn/store.py) for
        candidate comparison. The worker scores them through the
        candidate's ``predict_encoded``, so a freshly staged candidate can
        be judged against the RECENT WINDOW immediately instead of waiting
        for future traffic to sample — what makes warp-speed game days
        (and fast drift response) possible. Same non-blocking bounded-
        queue contract as ``submit``."""
        cand = self._candidate
        if cand is None:
            return False
        try:
            self._queue.put_nowait(
                (cand, (np.asarray(ids), np.asarray(counts)),
                 np.asarray(labels), np.asarray(probs), "encoded", ""))
            return True
        except queue.Full:
            with self._lock:
                self._dropped += 1
            return False

    # ------------------------------------------------------------------
    # worker
    # ------------------------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                with self._region:
                    self._score_item(item)
            except Exception as e:  # noqa: BLE001 — shadow must never kill serving
                with self._lock:
                    self._errors += 1
                log.warning("shadow scoring failed (candidate v%s): %s",
                            item[0][0], e)
            finally:
                self._queue.task_done()

    def _score_item(self, item) -> None:
        (version, pipeline), payloads, labels, probs, raw, text_field = item
        if self._candidate is None or self._candidate[0] != version:
            return  # candidate was cleared/replaced while queued: stale
        if raw == "encoded":
            # Window-replay batch (submit_encoded): score the candidate on
            # the stored packed rows directly — no text exists to decode.
            ids, counts = payloads
            if ids.shape[0] == 0:
                return
            cand = pipeline.predict_encoded(ids, counts)
            self._accumulate(version, np.asarray(labels), np.asarray(probs),
                             np.asarray(cand.labels),
                             np.asarray(cand.probabilities, np.float64))
            return
        if raw:
            texts: List[str] = []
            keep: List[int] = []
            for i, value in enumerate(payloads):
                try:
                    obj = json.loads(value)
                except ValueError:
                    continue
                text = obj.get(text_field) if isinstance(obj, dict) else None
                if isinstance(text, str):
                    texts.append(text)
                    keep.append(i)
            labels = np.asarray(labels)[keep]
            probs = np.asarray(probs)[keep]
        else:
            texts = list(payloads)
            labels = np.asarray(labels)
            probs = np.asarray(probs)
        if not texts:
            return
        cand = pipeline.predict(texts)
        self._accumulate(version, np.asarray(labels), np.asarray(probs),
                         np.asarray(cand.labels),
                         np.asarray(cand.probabilities, np.float64))

    def _accumulate(self, version, labels, probs, c_labels, c_probs) -> None:
        """Fold one scored batch into the cumulative AND windowed stats
        (shared by the live-traffic and encoded-replay paths)."""
        p_probs = np.asarray(probs, np.float64)
        p_hist = score_histogram(p_probs, self.n_bins)
        c_hist = score_histogram(c_probs, self.n_bins)
        n = int(labels.shape[0])
        agree = int(np.sum(c_labels == labels))
        abs_dp = float(np.sum(np.abs(c_probs - p_probs)))
        with self._lock:
            if self._candidate is None or self._candidate[0] != version:
                return
            self._batches += 1
            self._rows += n
            self._agree += agree
            self._abs_dp_sum += abs_dp
            self._primary_flagged += int(np.sum(labels != 0))
            self._candidate_flagged += int(np.sum(c_labels != 0))
            self._primary_hist += p_hist
            self._candidate_hist += c_hist
            self._window.append((n, agree, abs_dp, p_hist, c_hist))

    # ------------------------------------------------------------------
    # observability / teardown
    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """Point-in-time divergence stats (the health()/promotion input)."""
        with self._lock:
            rows = self._rows
            cand = self._candidate
            # Windowed (recent-batch) divergence beside the cumulative
            # stats: a month of early agreement must not mask an hour of
            # drift (docs/online_learning.md; the learn-loop drift rules
            # and the shadow_disagreement_burn sentinel read this).
            w_rows = sum(t[0] for t in self._window)
            w_agree = sum(t[1] for t in self._window)
            w_dp = sum(t[2] for t in self._window)
            if self._window:
                w_p_hist = np.sum([t[3] for t in self._window], axis=0)
                w_c_hist = np.sum([t[4] for t in self._window], axis=0)
            else:
                w_p_hist = w_c_hist = np.zeros(self.n_bins, np.float64)
            window = {
                "batches": len(self._window),
                "max_batches": self.window_batches,
                "rows": w_rows,
                "agreement_rate": (w_agree / w_rows) if w_rows else None,
                "mean_abs_dp": (w_dp / w_rows) if w_rows else None,
                "psi": population_stability_index(w_p_hist, w_c_hist)
                       if w_rows else None,
            }
            snap = {
                "candidate_version": cand[0] if cand is not None else None,
                "batches": self._batches,
                "rows": rows,
                "disagreed": rows - self._agree,
                "window": window,
                "agreement_rate": (self._agree / rows) if rows else None,
                "mean_abs_dp": (self._abs_dp_sum / rows) if rows else None,
                "flag_rate_primary": (self._primary_flagged / rows) if rows else None,
                "flag_rate_candidate": (self._candidate_flagged / rows) if rows else None,
                "flag_rate_delta": ((self._candidate_flagged - self._primary_flagged)
                                    / rows) if rows else None,
                "psi": population_stability_index(self._primary_hist,
                                                  self._candidate_hist)
                       if rows else None,
                "dropped": self._dropped,
                "errors": self._errors,
                "sampled_out": self._sampled_out,
                "queue_depth": self._queue.qsize(),
                "sample": self.sample,
                "window_sec": self._clock() - self._started_at,
                "score_hist_primary": self._primary_hist.tolist(),
                "score_hist_candidate": self._candidate_hist.tolist(),
            }
        return snap

    def drain(self, timeout: float = 10.0) -> bool:
        """Wait until every queued batch is scored (tests/orderly teardown)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._queue.unfinished_tasks == 0:
                return True
            time.sleep(0.01)
        return self._queue.unfinished_tasks == 0

    def close(self, timeout: float = 10.0) -> bool:
        """Drain (bounded) then stop the worker thread."""
        drained = self.drain(timeout)
        self._stop.set()
        self._thread.join(timeout=5.0)
        return drained and not self._thread.is_alive()
