"""Scenario harness: seeded traffic, recorded-trace replay, game-day SLO
gates (docs/scenarios.md).

The chaos layer injects faults; this package injects *realistic traffic*
and judges the system against declared SLOs:

* scenarios/clock.py — one seeded timeline (virtual time + per-component
  seed derivation) that traffic, ``FaultPlan``, and ``WorkerDeathPlan``
  all compose on;
* scenarios/traffic.py — bit-reproducible generators (steady, diurnal,
  flash crowd, fraud-campaign waves, hot-key skew) and the single
  scenario-feeder thread;
* scenarios/record.py / replay.py — serve ``--trace-record`` recordings
  (the SpanRing as JSONL) replayed with original or warped timing,
  reproducing the original run's row key set exactly;
* scenarios/slo.py — declarative pass/fail gates (zero-loss/zero-dup
  multiset accounting, latency bounds, breaker/shed behavior) evaluated
  from run evidence;
* scenarios/gameday.py — scripted multi-failure scenarios as data, a
  named catalog, and the CLI gate (exit nonzero on violation) that the
  bench ``scenarios`` section and the CI ``scenario-smoke`` job run.
"""

from fraud_detection_tpu.scenarios.clock import ScenarioClock, derive_seed
from fraud_detection_tpu.scenarios.gameday import (CATALOG, AutoscaleSpec,
                                                   ChaosSpec,
                                                   ExpectedDetection,
                                                   GameDay, GameDayResult,
                                                   KillSpec, LearnSpec,
                                                   SentinelSpec,
                                                   get_scenario,
                                                   parse_scenario_ref,
                                                   run_gameday)
from fraud_detection_tpu.scenarios.labels import LabelFeeder
from fraud_detection_tpu.scenarios.record import (dump_tracer,
                                                  load_recording,
                                                  render_recording)
from fraud_detection_tpu.scenarios.replay import run_replay
from fraud_detection_tpu.scenarios.slo import (SloReport, SloSpec, evaluate,
                                               parse_slo)
from fraud_detection_tpu.scenarios.traffic import (CampaignWave, DiurnalLoad,
                                                   DriftCampaign, FlashCrowd,
                                                   SteadyLoad,
                                                   TimelineAction,
                                                   TrafficEvent,
                                                   TrafficFeeder, TrafficSpec,
                                                   compose, generate)

__all__ = [
    "AutoscaleSpec",
    "CATALOG", "CampaignWave", "ChaosSpec", "DiurnalLoad", "DriftCampaign",
    "ExpectedDetection", "FlashCrowd", "GameDay", "GameDayResult",
    "KillSpec", "LabelFeeder", "LearnSpec", "ScenarioClock", "SentinelSpec",
    "SloReport",
    "SloSpec", "SteadyLoad", "TimelineAction", "TrafficEvent",
    "TrafficFeeder", "TrafficSpec", "compose", "derive_seed", "dump_tracer",
    "evaluate", "generate", "get_scenario", "load_recording", "parse_slo",
    "parse_scenario_ref", "render_recording", "run_gameday", "run_replay",
]
