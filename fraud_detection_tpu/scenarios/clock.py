"""ScenarioClock: the one seeded timeline every scenario component shares.

A scenario composes three kinds of seeded machinery — traffic generation
(scenarios/traffic.py), broker fault injection (stream/faults.py
``FaultPlan``), and whole-worker deaths (``WorkerDeathPlan``) — and the
harness's reproducibility claim is only as strong as its weakest seed
discipline. The clock centralizes both halves of that discipline:

* **Seed derivation.** ``rng(name)`` / ``derive_seed(name)`` hand each
  component an independent deterministic stream derived from the ONE
  scenario seed via a stable hash (sha256 — NOT Python's ``hash()``, whose
  str/bytes randomization would change schedules across processes). Adding
  a component, or reordering construction, never perturbs any other
  component's draws — the failure mode a single shared ``random.Random``
  consumed in call order cannot avoid across refactors.
* **Virtual time.** Traffic events and timeline actions are scheduled at
  *virtual* seconds from scenario start. ``advance_to(t)`` maps virtual to
  wall time through ``time_scale``: 1.0 replays in real time, 0.5 at double
  speed, and **0.0 is warp mode** — no sleeping at all, the whole schedule
  is emitted as fast as the consumer drains it (what tests and the CI smoke
  run, paying zero wall-clock for a "two-minute" scenario). The EVENT
  timeline (what happens, in what order, with what payloads) is identical
  in every mode; only the pacing differs.

The clock is owned and driven by the single scenario-feeder thread
(scenarios/traffic.py); ``now()`` is a cross-thread-safe monotonic read.
"""

from __future__ import annotations

import hashlib
import random
import time
from typing import Callable


def derive_seed(seed: int, name: str) -> int:
    """A 63-bit child seed from (seed, name), stable across processes and
    Python versions (sha256, not the randomized builtin hash)."""
    digest = hashlib.sha256(f"{seed}:{name}".encode()).digest()
    return int.from_bytes(digest[:8], "big") >> 1


class ScenarioClock:
    """Virtual scenario time + deterministic per-component seed streams."""

    def __init__(self, seed: int = 0, *, time_scale: float = 0.0,
                 sleep: Callable[[float], None] = time.sleep,
                 wall: Callable[[], float] = time.monotonic):
        if time_scale < 0:
            raise ValueError(f"time_scale must be >= 0, got {time_scale}")
        self.seed = seed
        self.time_scale = time_scale
        self._sleep = sleep
        self._wall = wall
        self._started_at: float = wall()
        self._now = 0.0     # virtual seconds since start (monotonic float)

    # -- seeds ----------------------------------------------------------

    def derive_seed(self, name: str) -> int:
        """Deterministic child seed for a named component (fault plan,
        death plan, a traffic spec's draw stream, ...)."""
        return derive_seed(self.seed, name)

    def rng(self, name: str) -> random.Random:
        """An independent seeded stream for a named component."""
        return random.Random(self.derive_seed(name))

    # -- virtual time ---------------------------------------------------

    def start(self) -> None:
        """(Re)anchor virtual t=0 at the current wall clock — call when
        the scenario actually begins consuming the timeline."""
        self._started_at = self._wall()
        self._now = 0.0

    def now(self) -> float:
        """Current virtual time (last advanced-to point)."""
        return self._now

    def advance_to(self, t_virtual: float) -> None:
        """Advance the timeline to ``t_virtual`` seconds after start: in
        warp mode (time_scale 0) this just moves the cursor; otherwise it
        sleeps out whatever scaled wall time remains. Never goes
        backwards."""
        if t_virtual <= self._now:
            return
        if self.time_scale > 0.0:
            target_wall = self._started_at + t_virtual * self.time_scale
            remaining = target_wall - self._wall()
            if remaining > 0:
                self._sleep(remaining)
        self._now = t_virtual
