"""Game days: scripted multi-failure scenarios as data, with SLO gates.

A :class:`GameDay` declares everything about a run — seeded traffic specs
(scenarios/traffic.py), broker faults (:class:`ChaosSpec` →
stream/faults.py ``FaultPlan``), whole-worker deaths (:class:`KillSpec` →
``WorkerDeathPlan``), a scripted hot swap, scheduler/DLQ config — plus the
pass/fail :class:`~fraud_detection_tpu.scenarios.slo.SloSpec` gates judged
from the run's evidence. :func:`run_gameday` executes it against a real
in-process serving stack and returns a :class:`GameDayResult` whose ``ok``
bit is the game day's verdict. Every seeded component derives its stream
from the ONE scenario seed through the :class:`ScenarioClock`, so a game
day is reproducible end to end: same seed ⇒ same traffic bytes, same fault
schedule, same death draws, same timeline.

Two runner modes, chosen by the declaration:

* **fleet** (``workers >= 2`` or a kill spec): ``Fleet.in_process`` —
  partition-owning workers under the lease coordinator, tracing on, the
  seeded death plan armed, traffic fed live by the scenario-feeder thread.
  Chaos here is restricted to NON-LETHAL faults (duplicates, corruption,
  latency, commit fences, lossy flushes): a poll transport error or flush
  crash is an unhandled worker death in the fleet, which is the KILL
  spec's job to script, not the fault plan's.
* **single-engine** (otherwise): one supervised engine
  (``run_supervised``), where the FULL fault vocabulary applies (the
  supervisor is the recovery mechanism under test), and where the explain
  breaker can be exercised: ``breaker_threshold`` wires a deterministic
  dead explain backend (:class:`FlakyExplainBackend`) behind the PR 1
  circuit breaker, so a campaign wave's flagged burst trips it while
  classification keeps flowing.

The named catalog (:data:`CATALOG`) is the regression surface: the bench
``scenarios`` section and the CI ``scenario-smoke`` job run catalog
entries and commit the verdicts; ``serve --scenario NAME[:seed]`` drives
one against a live serve run. CLI::

    python -m fraud_detection_tpu.scenarios.gameday --name campaign_kill_swap
    python -m fraud_detection_tpu.scenarios.gameday --list

exits 0 on a passing verdict, 1 on any failed SLO — the exit code IS the
game-day gate (the CI smoke also verifies a deliberately broken SLO fails
nonzero, so the gate provably gates).
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from fraud_detection_tpu.scenarios.clock import ScenarioClock
from fraud_detection_tpu.scenarios.slo import (SloReport, SloSpec, evaluate,
                                               parse_slo)
from fraud_detection_tpu.scenarios.traffic import (CampaignWave,
                                                   DiurnalLoad,
                                                   DriftCampaign, FlashCrowd,
                                                   SteadyLoad,
                                                   TimelineAction,
                                                   TrafficFeeder, TrafficSpec,
                                                   compose)

INPUT_TOPIC = "scenario-in"
OUTPUT_TOPIC = "scenario-out"
DLQ_TOPIC = "scenario-dlq"
ANNOTATIONS_TOPIC = "scenario-out-annotations"
FEEDBACK_TOPIC = "scenario-feedback"


class FlakyExplainBackend:
    """A deterministically DEAD explain backend: every call raises, like
    an LLM endpoint mid-outage. Wrapped in the circuit breaker it turns a
    campaign wave's flagged burst into the breaker-trip scenario — the
    gate asserts the breaker opened AND classification never stopped."""

    def __init__(self):
        self.calls = 0

    def _fail(self):
        self.calls += 1
        raise ConnectionError(
            "scenario: explain backend down (scripted outage)")

    def chat(self, messages, **kwargs) -> str:
        self._fail()

    def generate(self, prompt: str, **kwargs) -> str:
        self._fail()


@dataclass(frozen=True)
class KillSpec:
    """Seeded whole-worker deaths (stream/faults.py WorkerDeathPlan);
    the seed derives from the scenario clock."""

    kills: int = 1
    modes: Tuple[str, ...] = ("graceful", "crash")
    min_polls: int = 2
    max_polls: int = 8


@dataclass(frozen=True)
class CoordKillSpec:
    """Seeded coordinator-leader deaths (stream/faults.py
    CoordinatorKillSpec); the seed derives from the scenario clock. Kill
    ticks count LEADER ticks, so a second kill lands on the successor —
    ``kills=2`` scripts consecutive failovers. Crash mode leaves no
    dying-breath snapshot: detection waits out ``role_ttl``, which is
    why the catalog's crash scenarios keep role_ttl above the sentinel's
    fast window (the stale rule must see frozen ticks span it)."""

    kills: int = 1
    modes: Tuple[str, ...] = ("graceful", "crash")
    min_ticks: int = 3
    max_ticks: int = 10


@dataclass(frozen=True)
class AutoscaleSpec:
    """Closed-loop elasticity as scenario data (fleet/autoscale/,
    docs/autoscaling.md): the ScalePolicy bounds/hysteresis the fleet
    runner arms, plus the declared surge onset the reaction-latency
    evidence measures from. The autoscaler reads the game day's OWN
    sentinel (``fleet_watermark_burn`` out, ``fleet_idle`` in), so an
    elastic scenario must declare a :class:`SentinelSpec` — the signals
    it scales on are the ones the run's watchdog judges."""

    min_workers: int = 1
    max_workers: int = 4
    cooldown_s: float = 1.0
    out_for_s: float = 0.0
    in_for_s: float = 0.0
    step: int = 1
    # Declared surge onset (virtual s): origin for the
    # ``autoscale_reaction_s`` evidence (first scale_out.at - surge_at_s).
    surge_at_s: float = 0.0

    def policy_kwargs(self) -> dict:
        return {"min_workers": self.min_workers,
                "max_workers": self.max_workers,
                "cooldown_s": self.cooldown_s,
                "out_for_s": self.out_for_s, "in_for_s": self.in_for_s,
                "step": self.step}


@dataclass(frozen=True)
class ExpectedDetection:
    """One seeded fault class and the alert that must catch it: the
    sentinel gate asserts rule ``rule`` FIRES within ``within_s``
    sentinel-clock seconds of ``fault_at_s`` (the fault's virtual
    injection time). Bounds are chosen to hold in BOTH pacing modes: a
    warp run (time_scale 0) collapses the feed to its end stamp and then
    advances one virtual tick per evaluation during the drain, so a warp
    detection latency is bounded below by (timeline end - fault time)."""

    rule: str
    fault_at_s: float = 0.0
    within_s: float = 10.0


@dataclass(frozen=True)
class SentinelSpec:
    """The game day's watchdog (obs/sentinel/, docs/observability.md):
    which rules run, at what virtual cadence, and what they must detect.
    Empty ``rules`` resolves to the default pack (single-engine mode) or
    the fleet pack (fleet mode) with windows scaled to game-day
    durations. ``zero_incidents`` is the clean-control-arm gate: the run
    must end with ``alerts.fired == 0`` (the false-positive gate)."""

    interval_s: float = 0.25
    rules: Tuple = ()                     # obs.sentinel.AlertRule tuple
    expect: Tuple[ExpectedDetection, ...] = ()
    zero_incidents: bool = False

    def __post_init__(self):
        if self.interval_s <= 0:
            raise ValueError(
                f"sentinel interval_s must be > 0, got {self.interval_s}")
        if self.zero_incidents and self.expect:
            raise ValueError(
                "a sentinel spec cannot both expect detections and gate "
                "on zero incidents")

    def resolve_rules(self, fleet_mode: bool) -> Tuple:
        from fraud_detection_tpu.obs.sentinel import (default_rule_pack,
                                                      fleet_rule_pack)

        if self.rules:
            return tuple(self.rules)
        # Game-day-scaled windows: catalog scenarios run seconds, not
        # hours — fast/slow burn windows and hysteresis shrink to match,
        # and the latency/stall limits widen past the warp-mode backlog
        # artifacts (a warp feed enqueues the whole timeline at once, so
        # enqueue->produce latency legitimately reaches seconds).
        if fleet_mode:
            # fast_s is also the delta-observation window: a worker-death
            # membership drop (-1) stays judgeable for fast_s virtual
            # seconds. The sentinel samples from a plain Python thread,
            # and on a 1-core host the GIL-releasing compute threads can
            # starve it for whole wall-seconds mid-drain — a 2 s window
            # can close between two samples while the while-gate's
            # backlog still exists. 8 s keeps the drop in-window for the
            # rest of a catalog run without loosening the gate itself
            # (the clean-drain exit still never fires: its drop happens
            # at committed_lag == 0, and the gate is judged at the
            # CURRENT sample). coordinator_absence is the opposite kind
            # of window — stale only fires once ticks sat frozen for the
            # WHOLE span, so it must stay shorter than the interregnum
            # it catches (~role_ttl); hence the separate stale_s.
            return fleet_rule_pack(backlog_limit=20000.0, fast_s=8.0,
                                   slow_s=16.0, resolve_s=1.0,
                                   stale_s=2.0)
        return default_rule_pack(fast_s=1.0, slow_s=4.0, for_s=0.0,
                                 resolve_s=1.0, p99_ms=60000.0,
                                 stall_s=30.0, dlq_limit=0.0005)


@dataclass(frozen=True)
class LearnSpec:
    """The closed learning loop, declared as scenario data
    (learn/, docs/online_learning.md). The runner publishes the pipeline
    as v1 in a fresh registry, wires the label lane (the
    scenarios/labels.py ground-truth oracle feeds ``feedback_topic``),
    runs the learn-lane beside the engine, and rides the REAL
    ``LifecycleController`` stage→shadow→judge→promote path — ``policy``
    is the PR 2 ``PromotionPolicy`` spec string the auto-promotion gates
    run with (a drift-correcting candidate legitimately disagrees with
    the drifted primary, so the drift-tuned defaults allow more
    disagreement than a like-for-like rollout would)."""

    min_labeled: int = 120          # evidence floor before any retrain
    min_new_labels: int = 32
    error_threshold: float = 0.12   # drift trigger (recent label error)
    error_window: int = 256
    refresh_rounds: int = 6
    window: int = 8192
    label_delay_s: float = 0.2      # virtual label latency
    policy: str = ("min_batches=1,min_rows=128,max_disagreement=0.7,"
                   "max_psi=50.0,max_flag_rate_delta=0.8")
    drift_at_s: float = 0.0         # drift onset (promotion-latency origin)
    promote_within_s: float = 60.0  # virtual drift->promotion bound
    settle_s: float = 120.0         # wall bound for retrain+judge to land

    def __post_init__(self):
        if self.settle_s <= 0:
            raise ValueError(f"settle_s must be > 0, got {self.settle_s}")


@dataclass(frozen=True)
class ChaosSpec:
    """Seeded broker-fault rates (stream/faults.py FaultPlan). The
    lethal kinds (poll errors, flush crashes) are single-engine only —
    GameDay validation enforces it (see module docstring)."""

    poll_error_rate: float = 0.0
    latency_spike_rate: float = 0.0
    duplicate_rate: float = 0.0
    corrupt_rate: float = 0.0
    flush_fail_rate: float = 0.0
    flush_crash_rate: float = 0.0
    commit_fence_rate: float = 0.0
    max_faults: int = 40

    @property
    def lethal(self) -> bool:
        return self.poll_error_rate > 0 or self.flush_crash_rate > 0


@dataclass(frozen=True)
class GameDay:
    """One scripted scenario, declared as data (see module docstring)."""

    name: str
    description: str
    traffic: Tuple[TrafficSpec, ...]
    slos: Tuple[SloSpec, ...]
    seed: int = 0
    partitions: int = 4
    workers: int = 1
    batch_size: int = 256
    max_wait: float = 0.02
    sched: Optional[object] = None        # sched.SchedulerConfig
    dlq: bool = False
    kills: Optional[KillSpec] = None
    # Coordinator succession (fleet/control.py, docs/fleet.md
    # "Coordinator succession"): candidates >= 2 runs the fleet under a
    # SuccessionCoordinator — the coordinator role itself is leased and
    # coordinator_kills scripts the leader's death; a standby candidate
    # must win the term election and inherit assignment state from the
    # compacted control topic. role_ttl is the vacancy-detection window
    # (defaults to lease_ttl / 2 inside the coordinator).
    candidates: int = 1
    role_ttl: Optional[float] = None
    coordinator_kills: Optional[CoordKillSpec] = None
    # Closed-loop autoscaling (fleet/autoscale/, docs/autoscaling.md):
    # the fleet sizes itself from the run's sentinel signals — scale-out
    # on the burn, voluntary-leave scale-in on sustained idle, every
    # decision term-stamped on the control lane and judged by the SLOs
    # over the evidence's ``autoscale`` block.
    autoscale: Optional[AutoscaleSpec] = None
    # Declared pacing: elasticity is judged against the SLOPE of the
    # load, so elastic scenarios pin time_scale (1.0 = real time) instead
    # of inheriting the caller's warp default — a warp feed lands the
    # whole tide in an instant and there is no curve left to track. An
    # explicit nonzero --time-scale still wins.
    time_scale: Optional[float] = None
    chaos: Optional[ChaosSpec] = None
    hot_swap_at: Optional[float] = None   # virtual seconds
    breaker_threshold: Optional[int] = None
    # Slot-based continuous-batching explain lane (explain/slotserve/,
    # docs/explain_serving.md): N decode slots serve every flagged row
    # through the async annotation lane; evidence gains the coverage
    # accounting the explain_coverage gate judges.
    explain_slots: Optional[int] = None
    explain_queue: int = 48               # lane queue bound (small = drops
                                          # exercised; every drop records)
    explain_tokens: int = 12
    # Paged-KV variant of the slotserve lane (docs/explain_serving.md
    # "Paged KV and prefix sharing"): the lane's KV cache becomes a
    # refcounted page pool with the shared explain preamble prefilled
    # once, and ``explain_kv_pages`` caps the pool — pick a budget where
    # the contiguous per-slot cache could NOT fit ``explain_slots`` slots
    # and the coverage gate proves paging holds the line anyway.
    explain_paged: bool = False
    explain_kv_pages: Optional[int] = None
    # The run's watchdog (obs/sentinel/): rules evaluated on the scenario
    # clock while the game day runs, with detects_within gates per seeded
    # fault class — or the zero-incident false-positive gate on the clean
    # control arm (docs/observability.md "Detection-latency gates").
    sentinel: Optional[SentinelSpec] = None
    # The closed learning loop (learn/, docs/online_learning.md): window
    # store + label lane + windowed retrain + auto shadow->promote
    # through the registry lifecycle — single-engine only, and the
    # pipeline must be a boosted-tree model (the warm-start refresh's
    # input): ``model`` picks the demo family.
    learn: Optional[LearnSpec] = None
    model: str = "lr"
    lease_ttl: float = 1.0
    supervise: int = 25
    idle_timeout: float = 1.0

    def __post_init__(self):
        if not self.traffic:
            raise ValueError(f"game day {self.name!r} declares no traffic")
        if self.workers < 1:
            raise ValueError(f"workers must be >= 1, got {self.workers}")
        if self.fleet_mode:
            if self.breaker_threshold is not None:
                raise ValueError(
                    f"game day {self.name!r}: the explain breaker lane is "
                    "single-engine only (the fleet does not wire explain)")
            if self.explain_slots is not None:
                raise ValueError(
                    f"game day {self.name!r}: the slotserve explain lane "
                    "is single-engine only (the fleet does not wire "
                    "explain)")
            if self.chaos is not None and self.chaos.lethal:
                raise ValueError(
                    f"game day {self.name!r}: poll errors / flush crashes "
                    "kill fleet workers outright — script worker deaths "
                    "with KillSpec instead")
        elif self.kills is not None:
            raise ValueError(
                f"game day {self.name!r}: worker kills need the fleet "
                "runner (workers >= 2)")
        if self.candidates < 1:
            raise ValueError(
                f"candidates must be >= 1, got {self.candidates}")
        if not self.fleet_mode and (self.candidates > 1
                                    or self.coordinator_kills is not None):
            raise ValueError(
                f"game day {self.name!r}: coordinator succession needs "
                "the fleet runner (workers >= 2)")
        if self.coordinator_kills is not None:
            if self.candidates < 2:
                raise ValueError(
                    f"game day {self.name!r}: killing the coordinator "
                    "needs a standby to succeed it (candidates >= 2)")
            if self.coordinator_kills.kills >= self.candidates:
                raise ValueError(
                    f"game day {self.name!r}: "
                    f"{self.coordinator_kills.kills} coordinator kills "
                    f"with {self.candidates} candidates leaves nobody to "
                    "coordinate")
        if self.breaker_threshold is not None and self.explain_slots is not None:
            raise ValueError(
                f"game day {self.name!r}: breaker_threshold scripts a DEAD "
                "explain backend; pick it or explain_slots, not both "
                "(breaker-over-slotserve is pinned at the engine level in "
                "tests/test_slotserve.py)")
        if self.explain_slots is not None and self.explain_slots < 1:
            raise ValueError(
                f"game day {self.name!r}: explain_slots must be >= 1, "
                f"got {self.explain_slots}")
        if self.explain_paged and self.explain_slots is None:
            raise ValueError(
                f"game day {self.name!r}: explain_paged pages the "
                "slotserve lane's KV cache — it needs explain_slots")
        if self.explain_kv_pages is not None:
            if not self.explain_paged:
                raise ValueError(
                    f"game day {self.name!r}: explain_kv_pages caps the "
                    "paged pool; set explain_paged=True")
            if self.explain_kv_pages < 1:
                raise ValueError(
                    f"game day {self.name!r}: explain_kv_pages must be "
                    f">= 1, got {self.explain_kv_pages}")
        if self.learn is not None:
            if self.fleet_mode:
                raise ValueError(
                    f"game day {self.name!r}: the learn loop is "
                    "single-engine only (one registry/lifecycle per run)")
            if self.hot_swap_at is not None:
                raise ValueError(
                    f"game day {self.name!r}: learn owns the hot-swap "
                    "path (promotion IS the swap) — drop hot_swap_at")
            if self.model != "xgb":
                raise ValueError(
                    f"game day {self.name!r}: the learn loop warm-starts "
                    f"boosted trees; set model='xgb' (got {self.model!r})")
        if self.autoscale is not None:
            if not self.fleet_mode:
                raise ValueError(
                    f"game day {self.name!r}: autoscaling needs the fleet "
                    "runner (workers >= 2)")
            if self.sentinel is None:
                raise ValueError(
                    f"game day {self.name!r}: the autoscaler is signal-"
                    "driven — declare a SentinelSpec (the fleet pack "
                    "carries fleet_watermark_burn / fleet_idle)")
            a = self.autoscale
            if not (a.min_workers <= self.workers <= a.max_workers):
                raise ValueError(
                    f"game day {self.name!r}: workers ({self.workers}) "
                    f"must sit inside the autoscale bounds "
                    f"[{a.min_workers}, {a.max_workers}]")
        if self.time_scale is not None and self.time_scale <= 0:
            raise ValueError(
                f"game day {self.name!r}: declared time_scale must be "
                f"> 0 (got {self.time_scale}); leave it None for warp")
        if self.sentinel is not None and self.sentinel.expect:
            known = {r.name for r in
                     self.sentinel.resolve_rules(self.fleet_mode)}
            missing = [e.rule for e in self.sentinel.expect
                       if e.rule not in known]
            if missing:
                raise ValueError(
                    f"game day {self.name!r}: detects_within expects "
                    f"rules not in the sentinel pack: {missing} "
                    f"(pack: {sorted(known)})")

    @property
    def fleet_mode(self) -> bool:
        return self.workers >= 2

    def duration_s(self) -> float:
        return max(s.at_s + s.duration_s for s in self.traffic)


@dataclass
class GameDayResult:
    scenario: str
    seed: int
    mode: str
    report: SloReport
    evidence: dict              # summary evidence (key lists reduced)
    wall_s: float

    @property
    def ok(self) -> bool:
        return self.report.ok

    def as_dict(self) -> dict:
        return {"scenario": self.scenario, "seed": self.seed,
                "mode": self.mode, "ok": self.ok,
                "wall_s": round(self.wall_s, 3),
                "slo": self.report.as_dict(), "evidence": self.evidence}

    def table(self) -> str:
        head = (f"game day {self.scenario!r} (seed {self.seed}, "
                f"{self.mode}): {'PASS' if self.ok else 'FAIL'} "
                f"in {self.wall_s:.1f}s")
        return head + "\n" + self.report.table()


def _default_pipeline(batch_size: int, seed: int = 7, model: str = "lr"):
    from fraud_detection_tpu.models.pipeline import synthetic_demo_pipeline

    # Separable corpus: scenario rows are drawn from the same families,
    # so flagged-row lanes (breaker, annotation) see real pressure.
    return synthetic_demo_pipeline(
        batch_size=batch_size, n=300, seed=seed, num_features=2048,
        model=model,
        corpus_kwargs=dict(hard_fraction=0.0, label_noise=0.0))


def _fault_plan(gd: GameDay, clock: ScenarioClock):
    if gd.chaos is None:
        return None
    from fraud_detection_tpu.stream.faults import FaultPlan

    c = gd.chaos
    return FaultPlan(
        seed=clock.derive_seed("faults"),
        poll_error_rate=c.poll_error_rate,
        latency_spike_rate=c.latency_spike_rate,
        latency_spike_sec=0.001,
        duplicate_rate=c.duplicate_rate, corrupt_rate=c.corrupt_rate,
        flush_fail_rate=c.flush_fail_rate,
        flush_crash_rate=c.flush_crash_rate,
        commit_fence_rate=c.commit_fence_rate, max_faults=c.max_faults,
        sleep=((lambda s: None) if clock.time_scale == 0.0 else time.sleep))


def _swap_setup(gd: GameDay, pipeline, clock: ScenarioClock,
                actions: List[TimelineAction]):
    """Wrap the pipeline for the scripted hot swap and append the swap
    action: a v2 candidate (freshly trained, pre-built off-timeline so the
    timeline only pays the swap itself) lands mid-scenario through the
    zero-downtime RCU path every worker scores through."""
    if gd.hot_swap_at is None:
        return pipeline, None
    from fraud_detection_tpu.registry.hotswap import HotSwapPipeline

    hot = HotSwapPipeline(pipeline, version=1)
    candidate = _default_pipeline(gd.batch_size,
                                  seed=clock.derive_seed("candidate") % 9973)
    actions.append(TimelineAction(
        gd.hot_swap_at, "hot_swap_v2",
        lambda: hot.swap(candidate, version=2)))
    return hot, hot


def _learn_setup(gd: GameDay, pipeline, clock: ScenarioClock):
    """Registry-backed serving for the learn loop: the pipeline publishes
    as v1 in a fresh registry and every worker scores through ONE
    HotSwapPipeline — promotion IS the run's zero-downtime hot swap."""
    import tempfile

    from fraud_detection_tpu.registry import ModelRegistry
    from fraud_detection_tpu.registry.hotswap import HotSwapPipeline

    root = tempfile.mkdtemp(prefix="gameday-registry-")
    registry = ModelRegistry(root)
    registry.publish(pipeline.featurizer, pipeline.model,
                     metrics={"origin": f"gameday:{gd.name}:v1"})
    hot = HotSwapPipeline(pipeline, version=1)
    return hot, hot, {"registry": registry, "root": root}


def _wait_for_feed(feeder: TrafficFeeder, n: int, timeout: float = 30.0):
    """Block until the feeder has produced ``n`` rows (or finished/died):
    workers idle-exit on an empty topic, so traffic must visibly exist
    before the serving side starts its idle clock."""
    deadline = time.monotonic() + timeout
    target = min(n, len(feeder.events))
    while time.monotonic() < deadline:
        if feeder.fed >= target or feeder.error is not None:
            return
        if not feeder.alive():
            return
        time.sleep(0.005)


def run_gameday(gd: GameDay, *, pipeline=None, time_scale: float = 0.0,
                extra_slos: Sequence[SloSpec] = (),
                record_path: Optional[str] = None) -> GameDayResult:
    """Execute a game day and judge its SLOs (see module docstring)."""
    from fraud_detection_tpu.stream import InProcessBroker

    if time_scale == 0.0 and gd.time_scale is not None:
        # The scenario declares its pacing (elastic tides are judged
        # against the slope); an explicit nonzero --time-scale still wins.
        time_scale = gd.time_scale
    clock = ScenarioClock(gd.seed, time_scale=time_scale)
    events = compose(gd.traffic, clock)
    if not events:
        raise ValueError(f"game day {gd.name!r} generated zero rows")
    actions: List[TimelineAction] = []
    if pipeline is None:
        pipeline = _default_pipeline(gd.batch_size, model=gd.model)
    serving, hot = _swap_setup(gd, pipeline, clock, actions)
    learn_ctx = None
    if gd.learn is not None:
        serving, hot, learn_ctx = _learn_setup(gd, pipeline, clock)
    broker = InProcessBroker(num_partitions=gd.partitions)
    feeder = TrafficFeeder(broker.producer(), INPUT_TOPIC, events, clock,
                           actions=actions)
    plan = _fault_plan(gd, clock)

    t0 = time.perf_counter()
    if gd.fleet_mode:
        evidence = _run_fleet(gd, serving, broker, feeder, plan, clock)
    else:
        evidence = _run_single(gd, serving, broker, feeder, plan, clock,
                               learn_ctx)
    wall = time.perf_counter() - t0

    evidence.update({
        "scenario": gd.name, "seed": gd.seed,
        "mode": "fleet" if gd.fleet_mode else "single",
        "planned": len(events),
        "fed": feeder.fed,
        "feeder": feeder.stats(),
        "fed_keys": [e.key.decode() for e in events],
        "out_keys": [m.key.decode() for m in broker.messages(OUTPUT_TOPIC)
                     if m.key is not None],
        "dlq_keys": [m.key.decode() for m in broker.messages(DLQ_TOPIC)
                     if m.key is not None],
        "swaps": hot.swaps if hot is not None else 0,
        "chaos": plan.report() if plan is not None else None,
        "wall_s": round(wall, 3),
    })
    evidence["shed_fraction"] = round(
        (evidence.get("stats") or {}).get("shed", 0)
        / max(1, len(events)), 4)
    if feeder.error is not None:
        evidence.setdefault("errors", []).append(
            f"feeder: {feeder.error!r}")

    # Sentinel gates (docs/observability.md "Detection-latency gates"):
    # every expected detection becomes a detects_within SLO, and the
    # clean control arm gates on zero incidents — auto-derived from the
    # declaration so a scenario cannot declare a watchdog it forgets to
    # judge.
    auto_slos: List[SloSpec] = []
    if gd.sentinel is not None:
        evidence["fault_times"] = {e.rule: e.fault_at_s
                                   for e in gd.sentinel.expect}
        for e in gd.sentinel.expect:
            auto_slos.append(SloSpec(f"detects_{e.rule}",
                                     kind="detects_within", path=e.rule,
                                     limit=e.within_s))
        if gd.sentinel.zero_incidents:
            auto_slos.append(SloSpec("zero_incidents", path="alerts.fired",
                                     op="==", limit=0))
    # Spec-conformance gate: any run that recorded a control-lane
    # journal must replay cleanly against the FLEET_PROTOCOLS role
    # machines — auto-derived (like the sentinel gates above) so a
    # succession-enabled scenario cannot skip the audit.
    if evidence.get("conformance") is not None:
        auto_slos.append(SloSpec(
            "spec_conformance", path="conformance.violation_count",
            op="==", limit=0))

    report = evaluate(tuple(gd.slos) + tuple(auto_slos) + tuple(extra_slos),
                      evidence, scope="gameday")
    # Verdict-line summary: the full evidence fed the gates above; the
    # committed line keeps counts and the interesting blocks, not the key
    # lists or whole health trees.
    summary = {k: v for k, v in evidence.items()
               if k not in ("fed_keys", "out_keys", "dlq_keys", "health",
                            "stage_latency_ms", "traces", "alerts")}
    if record_path is not None:
        # The `flightcheck conform` recording: the control-lane journal
        # plus the verdicts it fed, in the evidence shape
        # conformance.extract_trace understands.
        with open(record_path, "w", encoding="utf-8") as f:
            json.dump({"scenario": gd.name, "seed": gd.seed,
                       "evidence": {
                           "succession": evidence.get("succession"),
                           "conformance": evidence.get("conformance"),
                       }}, f, indent=2)
    if isinstance(summary.get("succession"), dict):
        # The raw control-lane journal fed the spec_conformance gate
        # above (and `flightcheck conform` can replay it from a full
        # recording via --record); the committed verdict line keeps its
        # verdict, not its thousands of records.
        summary["succession"] = {k: v for k, v in
                                 summary["succession"].items()
                                 if k != "trace"}
    alerts = evidence.get("alerts")
    if isinstance(alerts, dict):
        summary["alerts"] = {
            "evaluations": alerts.get("evaluations"),
            "fired": alerts.get("fired"),
            "resolved": alerts.get("resolved"),
            "still_firing": alerts.get("still_firing"),
            "firing": alerts.get("firing"),
            "incidents": [{k: i.get(k) for k in
                           ("rule", "severity", "fired_at", "resolved_at")}
                          for i in alerts.get("incidents") or []],
        }
    summary["out_rows"] = len(evidence["out_keys"])
    summary["dlq_rows"] = len(evidence["dlq_keys"])
    summary["traces"] = [
        {k: t.get(k) for k in ("worker", "spans_open", "batches_traced",
                               "batches_closed", "ring_dropped")}
        for t in evidence.get("traces") or []]
    return GameDayResult(gd.name, gd.seed,
                         "fleet" if gd.fleet_mode else "single",
                         report, summary, wall)


def _run_fleet(gd: GameDay, serving, broker, feeder: TrafficFeeder,
               plan, clock: ScenarioClock) -> dict:
    from fraud_detection_tpu.fleet import Fleet
    from fraud_detection_tpu.stream.faults import (CoordinatorKillSpec,
                                                   WorkerDeathPlan)

    death_plan = None
    if gd.kills is not None:
        k = gd.kills
        death_plan = WorkerDeathPlan(
            seed=clock.derive_seed("deaths"), kills=k.kills,
            min_polls=k.min_polls, max_polls=k.max_polls, modes=k.modes)
    coord_kill = None
    if gd.coordinator_kills is not None:
        ck = gd.coordinator_kills
        coord_kill = CoordinatorKillSpec(
            seed=clock.derive_seed("coordinator_kills"), kills=ck.kills,
            min_ticks=ck.min_ticks, max_ticks=ck.max_ticks,
            modes=ck.modes)
    dlq_topic = DLQ_TOPIC if (gd.dlq or (
        gd.sched is not None and gd.sched.shed_policy != "none")) else None
    sentinel_kw = {}
    if gd.sentinel is not None:
        # Coordinator-level watchdog on the scenario clock: the fleet
        # sentinel stamps virtual seconds (same VirtualCadence semantics
        # as the single-engine runner, stepped at the monitor tick), so
        # detects_within judges warp and paced fleet runs on one axis.
        from fraud_detection_tpu.obs.sentinel import VirtualCadence

        sentinel_kw = dict(
            sentinel_rules=gd.sentinel.resolve_rules(fleet_mode=True),
            sentinel_clock=VirtualCadence(clock.now, 0.02))
    fleet = Fleet.in_process(
        broker, serving, INPUT_TOPIC, OUTPUT_TOPIC, gd.workers,
        batch_size=gd.batch_size, max_wait=gd.max_wait,
        sched_config=gd.sched, dlq_topic=dlq_topic,
        death_plan=death_plan, lease_ttl=gd.lease_ttl,
        heartbeat_interval=0.02, tick_interval=0.02,
        candidates=gd.candidates, role_ttl=gd.role_ttl,
        coordinator_kill=coord_kill,
        autoscale=(gd.autoscale.policy_kwargs()
                   if gd.autoscale is not None else None),
        fault_plan=plan, trace=True, trace_sample=1.0, **sentinel_kw)
    feeder.start()
    _wait_for_feed(feeder, n=min(64, len(feeder.events)))
    # Workers self-drain once input is idle AND the group's committed lag
    # clears; the idle window must outlast the timeline's longest paced gap.
    gaps = [b - a for a, b in zip([e.t for e in feeder.events],
                                  [e.t for e in feeder.events][1:])]
    idle = max(gd.idle_timeout,
               2.0 * clock.time_scale * max(gaps, default=0.0))
    out = fleet.run(idle_timeout=idle, join_timeout=300.0)
    feeder.join(timeout=120.0)
    # Scale-out reaction latency in VIRTUAL seconds: decision stamps ride
    # the sentinel's clock (VirtualCadence above), so the first
    # scale_out's ``at`` minus the DECLARED surge onset is comparable
    # across pacings and hosts (docs/autoscaling.md, the bench's
    # ``autoscale`` section trends it).
    reaction = None
    if gd.autoscale is not None:
        outs = [d for d in (out.get("autoscale") or {}).get(
                    "decisions") or [] if d.get("kind") == "scale_out"]
        if outs:
            reaction = round(outs[0]["at"] - gd.autoscale.surge_at_s, 3)
    return {
        "autoscale": out.get("autoscale"),
        "autoscale_reaction_s": reaction,
        "stats": {k: v for k, v in out.items()
                  if not isinstance(v, (dict, list))},
        "workers": out["workers"],
        "per_worker_processed": out["per_worker_processed"],
        "incarnations": out["incarnations"],
        "rebalances": out["rebalances"],
        "lease_expirations": out["lease_expirations"],
        "deaths": len(out["deaths"]),
        "death_plan": out.get("death_plan"),
        "errors": list(out["errors"]),
        "stage_latency_ms": out.get("stage_latency_ms"),
        "traces": [t.snapshot() for t in fleet.tracers.values()],
        "alerts": out.get("alerts"),
        "worker_alerts": out.get("worker_alerts"),
        "succession": out.get("succession"),
        "conformance": _conformance_block(out.get("succession")),
    }


def _conformance_block(succ) -> "Optional[dict]":
    """Replay the run's control-lane journal against the declared role
    machines (analysis/conformance.py) — the `spec_conformance` SLO
    gates on ``violation_count == 0``, so every succession-enabled game
    day proves the implementation and the model-checked spec agree."""
    if not isinstance(succ, dict) or not succ.get("trace"):
        return None
    from fraud_detection_tpu.analysis import conformance

    records, ctx = conformance.extract_trace(succ)
    violations = conformance.check_records(
        records, handoffs=ctx.get("handoffs"),
        lost=ctx.get("lost", 0), reordered=ctx.get("reordered", 0))
    return conformance.summarize(violations, len(records))


def _run_single(gd: GameDay, serving, broker, feeder: TrafficFeeder,
                plan, clock: ScenarioClock, learn_ctx=None) -> dict:
    from fraud_detection_tpu.obs.trace import RowTracer
    from fraud_detection_tpu.stream.engine import (StreamingClassifier,
                                                   run_supervised)

    tracer = RowTracer(worker="gd0", sample=1.0, capacity=65536)
    scheduler = None
    if gd.sched is not None:
        from fraud_detection_tpu.sched import AdaptiveScheduler

        scheduler = AdaptiveScheduler(gd.sched, gd.batch_size)
    dlq_topic = (DLQ_TOPIC if (gd.dlq or plan is not None
                               or (scheduler is not None and scheduler.sheds))
                 else None)
    breaker = None
    hook = None
    explain_service = None
    explain_async = gd.explain_slots is not None
    annotations_agg = {"submitted": 0, "annotated": 0, "dropped": 0,
                       "drop_records": 0, "backend_errors": 0}
    if gd.breaker_threshold is not None:
        from fraud_detection_tpu.explain import (CircuitBreakerBackend,
                                                 make_stream_explain_hook)

        breaker = CircuitBreakerBackend(
            FlakyExplainBackend(), failure_threshold=gd.breaker_threshold,
            probe_interval=600.0)
        hook = make_stream_explain_hook(breaker, max_tokens=32)
    elif explain_async:
        # Slotserve lane (docs/explain_serving.md): a tiny seeded on-pod
        # model serves every flagged row through the slot pool behind the
        # async annotation lane; the lane's SMALL queue (gd.explain_queue)
        # makes campaign waves exercise drop-OLDEST, and every drop leaves
        # a structured record — coverage stays exactly 1.0.
        from fraud_detection_tpu.explain.slotserve import (
            SlotServeService, make_slot_explain_hook)
        from fraud_detection_tpu.models.llm import (LanguageModel,
                                                    TransformerConfig)

        lm = LanguageModel.init_random(
            TransformerConfig(d_model=64, n_layers=2, n_heads=4, d_ff=128,
                              max_seq=1024),
            seed=clock.derive_seed("explain-lm") % (2 ** 31))
        # Paged variant: prompt_width widens to 448 so the ~293-token
        # shared explain preamble fits ahead of the transcript (at 256
        # the service degrades to unshared with a warning), and the page
        # pool is capped at gd.explain_kv_pages — a budget the scenario
        # picks so the contiguous cache could not fit this slot count.
        paged_kw: dict = {}
        width = 256
        if gd.explain_paged:
            width = 448
            paged_kw = {"paged": True, "page_size": 64}
            if gd.explain_kv_pages is not None:
                paged_kw["kv_pages"] = gd.explain_kv_pages
        explain_service = SlotServeService(
            lm, slots=gd.explain_slots, max_queue=4096,
            max_new_tokens=gd.explain_tokens, prompt_width=width,
            rowtrace=tracer, **paged_kw)
        hook = make_slot_explain_hook(explain_service,
                                      max_tokens=gd.explain_tokens)

    dlq_attempts: dict = {}
    engines: list = []

    # The closed learning loop (learn/, docs/online_learning.md): label
    # oracle -> feedback topic -> learn-lane window joins -> windowed
    # warm-started retrain -> registry publish -> the REAL
    # LifecycleController stages, shadow-judges, and auto-promotes.
    learn_loop = None
    shadow = None
    controller = None
    label_feeder = None
    watch_stop = None
    watch_thread = None
    if learn_ctx is not None:
        from fraud_detection_tpu.learn import LearnConfig, LearnLoop
        from fraud_detection_tpu.registry import (LifecycleController,
                                                  PromotionPolicy,
                                                  ShadowScorer)
        from fraud_detection_tpu.scenarios.labels import LabelFeeder

        ls = gd.learn
        shadow = ShadowScorer(max_queue=64, sample=1.0, window_batches=32)
        learn_loop = LearnLoop(
            feedback_consumer=broker.consumer([FEEDBACK_TOPIC], "learn"),
            registry=learn_ctx["registry"], hotswap=serving, shadow=shadow,
            config=LearnConfig(
                window=ls.window, min_labeled=ls.min_labeled,
                min_new_labels=ls.min_new_labels,
                error_threshold=ls.error_threshold,
                error_window=ls.error_window,
                refresh_rounds=ls.refresh_rounds, cooldown_s=1.0),
            now_fn=clock.now)
        controller = LifecycleController(
            learn_ctx["registry"], serving, shadow=shadow,
            policy=PromotionPolicy.parse(ls.policy),
            batch_size=gd.batch_size,
            health_fn=lambda: (engines[-1].health() if engines else None),
            on_transition=learn_loop.on_transition)
        learn_loop.bind_controller(controller)
        label_feeder = LabelFeeder(
            broker.consumer([INPUT_TOPIC], "scenario-labels"),
            broker.producer(), FEEDBACK_TOPIC, clock=clock,
            delay_s=ls.label_delay_s).start()
        watch_thread, watch_stop = controller.run_in_thread(interval=0.05)

    # The watchdog (obs/sentinel/): ONE sentinel shared across the
    # supervised incarnation chain (like the tracer and the poison
    # tracker), reading the LIVE engine's health on the scenario clock —
    # VirtualCadence stamps evaluations in virtual seconds, and the
    # driver's wall cadence scales with time_scale (warp runs evaluate
    # every interval_s WALL seconds during the drain, advancing one
    # virtual tick each), so warp and paced game days judge detection
    # latency on the same axis.
    sentinel = None
    sentinel_source = None
    finish_sentinel = lambda: None  # noqa: E731 — mirrors serve's finishers
    if gd.sentinel is not None:
        from fraud_detection_tpu.obs.sentinel import (ChainedHealthSource,
                                                      Sentinel,
                                                      VirtualCadence,
                                                      start_sentinel)

        # Chain-cumulative counters: a chaos run's restart chain must
        # read as monotonic burns + a supervisor.restarts counter, not as
        # per-incarnation resets the sampling cadence can miss.
        sentinel_source = ChainedHealthSource()
        sentinel = Sentinel(
            sentinel_source,
            gd.sentinel.resolve_rules(fleet_mode=False),
            clock=VirtualCadence(clock.now, gd.sentinel.interval_s),
            worker="gd0")
        wall_interval = gd.sentinel.interval_s * (
            clock.time_scale if clock.time_scale > 0 else 1.0)
        finish_sentinel = start_sentinel([sentinel], wall_interval)

    def harvest_annotations(engine) -> None:
        engine.close_annotations(timeout=120.0)
        s = engine.annotation_stats() or {}
        for k in annotations_agg:
            annotations_agg[k] += s.get(k, 0)

    def make_engine():
        consumer = broker.consumer([INPUT_TOPIC], "gameday")
        producer = broker.producer()
        if plan is not None:
            consumer, producer = plan.consumer(consumer), plan.producer(producer)
        if engines and explain_async:
            # One live lane at a time: drain + harvest the replaced
            # incarnation's counters (serve.py's make_engine contract).
            harvest_annotations(engines[-1])
        engine = StreamingClassifier(
            serving, consumer, producer, OUTPUT_TOPIC,
            batch_size=gd.batch_size, max_wait=gd.max_wait,
            explain_batch_fn=hook, breaker=breaker,
            explain_async=explain_async,
            annotations_producer=(broker.producer() if explain_async
                                  else None),
            annotations_topic=ANNOTATIONS_TOPIC,
            annotations_queue=gd.explain_queue,
            explain_service=explain_service,
            dlq_topic=dlq_topic, dlq_attempts=dlq_attempts,
            scheduler=scheduler, rowtrace=tracer, sentinel=sentinel,
            shadow=shadow, learn=learn_loop)
        engines.append(engine)
        if sentinel_source is not None:
            sentinel_source.attach(engine)
        return engine

    feeder.start()
    _wait_for_feed(feeder, n=min(64, len(feeder.events)))
    gaps = [b - a for a, b in zip([e.t for e in feeder.events],
                                  [e.t for e in feeder.events][1:])]
    idle = max(gd.idle_timeout,
               2.0 * clock.time_scale * max(gaps, default=0.0))
    backoff_rng = random.Random(clock.derive_seed("backoff"))
    sleep = ((lambda s: time.sleep(min(s, 0.01)))
             if clock.time_scale == 0.0 else time.sleep)
    from fraud_detection_tpu.stream.engine import StreamStats, _merge_stats

    total = StreamStats()
    errors: List[str] = []
    # The supervisor exits when input goes idle; re-enter while the feeder
    # is still producing (paced timelines have real gaps) or committed lag
    # remains — bounded rounds so a wedged run still terminates.
    for _ in range(5):
        try:
            stats = run_supervised(make_engine, max_restarts=gd.supervise,
                                   idle_timeout=idle, sleep=sleep,
                                   rng=backoff_rng)
            _merge_stats(total, stats)
            total.restarts += stats.restarts
        except Exception as e:  # noqa: BLE001 — verdict-level failure
            errors.append(repr(e))
            stats = getattr(e, "supervisor_stats", None)
            if stats is not None:
                _merge_stats(total, stats)
            break
        if (not feeder.alive()
                and broker.group_lag("gameday", [INPUT_TOPIC]) <= 0):
            break
    feeder.join(timeout=120.0)
    learn_out: Optional[dict] = None
    if learn_ctx is not None:
        learn_out = _settle_learn(gd, broker, learn_loop, shadow,
                                  controller, label_feeder, watch_stop,
                                  watch_thread, serving, learn_ctx)
    # Stop the watchdog with a FINAL evaluation pass, so a condition that
    # only became judgeable at the very end of the drain still transitions
    # before the verdict reads the snapshot.
    finish_sentinel()
    annotations = None
    explain_snap = None
    coverage = None
    if explain_async:
        if engines:
            harvest_annotations(engines[-1])
        explain_service.close(timeout=60.0)
        explain_snap = explain_service.snapshot()
        annotations = dict(annotations_agg)
        # THE slot-lane invariant: every flagged row handed to the lane is
        # explained (annotated) OR accounted by a structured drop record —
        # a bare drop counter would read as coverage < 1.0 here.
        coverage = round((annotations["annotated"]
                          + annotations["drop_records"])
                         / max(1, annotations["submitted"]), 6)
    health = engines[-1].health() if engines else {}
    out = {
        "stats": total.as_dict(),
        "health": health,
        "sched": scheduler.snapshot() if scheduler is not None else None,
        "breaker": breaker.snapshot() if breaker is not None else None,
        "flaky_backend_calls": (breaker.inner.calls
                                if breaker is not None else None),
        "annotations": annotations,
        "explain": explain_snap,
        "explain_coverage": coverage,
        "explain_accounting_exact": (
            None if explain_snap is None
            else explain_snap["admitted"] == (explain_snap["completed"]
                                              + explain_snap["dropped"])),
        "annotation_rows": (broker.topic_size(ANNOTATIONS_TOPIC)
                            if explain_async else None),
        "traces": [tracer.snapshot()],
        "alerts": sentinel.snapshot() if sentinel is not None else None,
        "errors": errors,
    }
    if learn_out is not None:
        out.update(learn_out)
    return out


def _settle_learn(gd: GameDay, broker, learn_loop, shadow, controller,
                  label_feeder, watch_stop, watch_thread, serving,
                  learn_ctx) -> dict:
    """Bounded post-traffic drain of the closed loop: let the label oracle
    catch up with the input topic, the lane consume its queues, the
    windowed retrain land, and the controller judge the candidate — then
    stop every learn-side thread and assemble the verdict evidence. A run
    whose policy refuses promotion converges here too (the state goes
    stable without a promote), so the negative CI arm terminates fast
    instead of burning the whole settle budget."""
    ls = gd.learn
    deadline = time.monotonic() + ls.settle_s
    # The ground-truth oracle must see every input row before it stops.
    while time.monotonic() < deadline and \
            broker.group_lag("scenario-labels", [INPUT_TOPIC]) > 0 and \
            label_feeder.error is None:
        time.sleep(0.02)
    time.sleep(0.05)           # let the last due labels produce
    label_feeder.join(timeout=30.0)
    stable = None
    stable_since = time.monotonic()
    while time.monotonic() < deadline:
        snap = learn_loop.snapshot()
        staged = serving.staged_version
        state = (snap["published"], snap["promoted"], snap["rejected"],
                 snap["rolled_back"], snap["in_flight"], staged,
                 shadow.snapshot()["rows"])
        if snap["promoted"] >= 1 and staged is None \
                and not snap["in_flight"]:
            break
        if state != stable:
            stable, stable_since = state, time.monotonic()
        elif (time.monotonic() - stable_since > 6.0
              and not snap["in_flight"] and snap["queue_depth"] == 0
              and broker.group_lag("learn", [FEEDBACK_TOPIC]) <= 0):
            break   # converged without a promotion (e.g. policy refused)
        time.sleep(0.05)
    if watch_stop is not None:
        watch_stop.set()
        watch_thread.join(timeout=10.0)
    learn_loop.close(timeout=120.0)
    shadow.close(timeout=30.0)
    snap = learn_loop.snapshot()
    events = list(controller.events)
    staged_versions = {e.get("version") for e in events
                       if e.get("event") == "stage"}
    judged = sum(1 for e in events if e.get("event") in
                 ("promote", "reject", "rollback"))
    audit_ok = (set(snap["published_versions"]) <= staged_versions
                and (not snap["published_versions"] or judged >= 1)
                and len(learn_ctx["registry"].read_audit()) >= len(events))
    promoted_at = snap["promoted_at_s"]
    latency = (round(promoted_at - ls.drift_at_s, 3)
               if promoted_at is not None else None)
    return {
        "learn": snap,
        "labels": label_feeder.stats(),
        "lifecycle": {
            "events": [{k: e.get(k) for k in ("event", "version",
                                              "reasons")}
                       for e in events],
            "active_version": serving.active_version,
            "staged_version": serving.staged_version,
            "swaps": serving.swaps,
            "audit_ok": audit_ok,
        },
        "learn_promotion_latency_s": latency,
        "registry_root": learn_ctx["root"],
    }


# ---------------------------------------------------------------------------
# the named catalog (bench `scenarios` section, CI scenario-smoke,
# serve --scenario, docs/scenarios.md)
# ---------------------------------------------------------------------------

def _sched_config(**kw):
    from fraud_detection_tpu.sched import SchedulerConfig

    # Cost-aware measurement is a perf-bench concern; the harness keeps
    # the fixed ladder so no scenario pays a rung-timing phase.
    return SchedulerConfig(cost_aware=False, **kw)


def _flash_crowd(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="flash_crowd",
        description="A 20x flash-crowd ramp against admission control: "
                    "the watermark + AIMD shed must bite on the ramp and "
                    "every shed row must land as an accounted DLQ record.",
        seed=seed,
        traffic=(FlashCrowd(name="crowd", duration_s=3.5, scam_fraction=0.2,
                            base_rate=120 * scale, peak_rate=2400 * scale,
                            ramp_at_s=0.6, ramp_s=0.5, hold_s=1.2,
                            decay_s=0.5),),
        # Watermark-led shedding: the p99 target is generous because warp
        # mode (time_scale 0) lands the whole spike in an instant — a
        # tight target would CoDel-deadline-shed nearly every row on age
        # alone and the verdict would measure the clock, not the ramp.
        sched=_sched_config(max_queue=800, shed_policy="adaptive",
                            target_p99_ms=4000.0),
        dlq=True,
        # The watchdog must CATCH the ramp: the shed-burn alert fires
        # within bounded virtual seconds of the flash crowd's onset.
        sentinel=SentinelSpec(expect=(
            ExpectedDetection("shed_burn", fault_at_s=0.6, within_s=12.0),)),
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            SloSpec("admission_shed_bit", path="stats.shed", op=">=",
                    limit=1),
            SloSpec("shed_budget", path="shed_fraction", op="<=",
                    limit=0.9),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _campaign_breaker(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="campaign_breaker",
        description="A correlated fraud-campaign wave with the explain "
                    "backend down: the circuit breaker must open and "
                    "classification must keep flowing, every row "
                    "accounted.",
        seed=seed,
        traffic=(
            SteadyLoad(name="baseline", rate=150 * scale, duration_s=3.0,
                       scam_fraction=0.1),
            CampaignWave(name="campaign", at_s=0.8, duration_s=2.2,
                         wave_rate=600 * scale, waves=2, wave_s=0.5,
                         gap_s=0.6),
        ),
        breaker_threshold=3,
        dlq=True,
        # The breaker trip is the seeded fault here: the breaker_open
        # delta rule must fire within bounded virtual seconds of the
        # campaign wave that drives the dead backend.
        sentinel=SentinelSpec(expect=(
            ExpectedDetection("breaker_open", fault_at_s=0.8,
                              within_s=12.0),)),
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            SloSpec("breaker_tripped", path="breaker.opens", op=">=",
                    limit=1),
            SloSpec("breaker_fast_fails", path="breaker.fast_fails",
                    op=">=", limit=1),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _campaign_kill_swap(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="campaign_kill_swap",
        description="THE game day: a fraud-campaign spike while a seeded "
                    "worker kill rebalances the fleet while a v2 model "
                    "hot-swaps in — zero-loss/zero-dup accounting must "
                    "hold through all three at once.",
        seed=seed,
        workers=2,
        partitions=4,
        # Two coordinator candidates: no kill is seeded here, but the
        # control lane rides the succession bus, so the run records a
        # conformance journal and the auto spec_conformance gate judges
        # it (ISSUE 20 — the spec audit must also cover a day whose
        # coordinator LIVES).
        candidates=2,
        kills=KillSpec(kills=1, modes=("graceful", "crash"), min_polls=2,
                       max_polls=6),
        hot_swap_at=1.2,
        # Short lease: a crash-mode kill is only OBSERVED at lease
        # expiry, and the worker_absence while-gate needs committed work
        # to remain at that instant — on a fast host a warp-fed run can
        # otherwise drain past the blind spot before the expiry lands
        # (the row count below sizes the drain for the same reason).
        lease_ttl=0.5,
        # The fleet watchdog must see the kill: membership shrank while
        # committed work remained (the while-gate separates the death
        # from the clean drain exit). Kill timing is poll-count-seeded,
        # not virtual-timed, so the bound covers the whole run.
        sentinel=SentinelSpec(expect=(
            ExpectedDetection("worker_absence", fault_at_s=0.0,
                              within_s=60.0),)),
        traffic=(
            SteadyLoad(name="baseline", rate=260 * scale, duration_s=4.0,
                       scam_fraction=0.15),
            CampaignWave(name="campaign", at_s=0.6, duration_s=2.9,
                         wave_rate=900 * scale, waves=2, wave_s=0.7,
                         gap_s=0.5),
        ),
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            SloSpec("worker_killed", path="deaths", op="==", limit=1,
                    scope="gameday"),
            SloSpec("hot_swap_landed", path="swaps", op=">=", limit=1,
                    scope="gameday"),
            SloSpec("p99_batch_s", path="stats.p99_batch_latency_sec",
                    op="<=", limit=30.0),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _coordinator_kill(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="coordinator_kill",
        description="The succession game day: a crash-mode coordinator "
                    "kill mid-campaign — while a seeded worker crash "
                    "holds committed work in flight — forces a standby "
                    "candidate to win the term election and reconstruct "
                    "assignment state from the compacted control topic; "
                    "zero-loss/zero-dup accounting must hold across the "
                    "interregnum and the coordinator_absence watchdog "
                    "must catch the dead brain.",
        seed=seed,
        workers=3,
        partitions=6,
        candidates=3,
        # Crash mode only: a graceful abdication leaves a dying-breath
        # snapshot and a near-zero interregnum, which the stale rule
        # cannot see. The crash leaves frozen coordinator ticks that the
        # watchdog must notice the hard way — by waiting out role_ttl.
        coordinator_kills=CoordKillSpec(kills=1, modes=("crash",),
                                        min_ticks=3, max_ticks=10),
        # A crash-killed WORKER keeps committed lag pinned above zero
        # through the interregnum (its lease cannot expire while the
        # coordinator is dead): that stuck lag is the while-gate
        # separating "brain dead with work remaining" from a clean
        # drain's legitimately idle coordinator. The pin must be
        # STRUCTURAL, not lucky: the coordinator dies within its first
        # few 20 ms ticks, long before the worker's ~1 s lease could
        # expire, and the worker dies within its first 3 polls — at
        # batch_size 64 that is at most 192 rows consumed against the
        # ~290 its two partitions carry at gate scale, so it always
        # leaves unreassignable backlog behind. Without that floor
        # (e.g. at the default 256-row batches) a single early poll can
        # drain the doomed worker's partitions entirely, the fleet
        # finishes inside role_ttl, and the run exits with no election
        # to judge.
        kills=KillSpec(kills=1, modes=("crash",), min_polls=2,
                       max_polls=3),
        batch_size=64,
        lease_ttl=1.0,
        # The vacancy window must OUTLAST the sentinel's fast stale
        # window (2.0 virtual s at game-day scaling): coordinator ticks
        # stay frozen for the whole role_ttl, so the stale rule sees a
        # genuinely spanned window before a successor revives the pulse.
        role_ttl=2.8,
        sentinel=SentinelSpec(expect=(
            ExpectedDetection("coordinator_absence", fault_at_s=0.0,
                              within_s=60.0),)),
        traffic=(
            SteadyLoad(name="baseline", rate=260 * scale, duration_s=4.0,
                       scam_fraction=0.15),
            CampaignWave(name="campaign", at_s=0.6, duration_s=2.9,
                         wave_rate=800 * scale, waves=2, wave_s=0.7,
                         gap_s=0.5),
        ),
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            SloSpec("worker_killed", path="deaths", op="==", limit=1,
                    scope="gameday"),
            SloSpec("coordinator_killed",
                    path="succession.kill_plan.killed.0.mode", op="==",
                    limit="crash", scope="gameday"),
            SloSpec("election_won", path="succession.elections", op=">=",
                    limit=1, scope="gameday"),
            SloSpec("term_advanced", path="succession.term", op=">=",
                    limit=2, scope="gameday"),
            # Wall-clock failover bound: vacancy detection (role_ttl)
            # plus election plus state reconstruction, with generous
            # headroom for slow CI hosts.
            SloSpec("failover_bounded_s",
                    path="succession.handoffs.0.failover_s", op="<=",
                    limit=30.0, scope="gameday"),
            SloSpec("control_zero_loss", path="succession.control.lost",
                    op="==", limit=0, scope="gameday"),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _campaign_explain(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="campaign_explain",
        description="A fraud-campaign wave drives the slotserve "
                    "continuous-batching explain lane: every flagged row "
                    "must be explained or leave a structured drop record "
                    "(explain_coverage == 1.0), slot accounting must be "
                    "exact, and p99 explain latency bounded.",
        seed=seed,
        traffic=(
            SteadyLoad(name="baseline", rate=100 * scale, duration_s=2.5,
                       scam_fraction=0.15),
            CampaignWave(name="campaign", at_s=0.5, duration_s=1.8,
                         wave_rate=400 * scale, waves=2, wave_s=0.5,
                         gap_s=0.4),
        ),
        explain_slots=8,
        explain_queue=48,
        explain_tokens=12,
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            # THE gate this scenario exists for: flagged rows handed to
            # the lane are annotated OR drop-recorded — never silently
            # sampled away.
            SloSpec("explain_coverage", path="explain_coverage", op="==",
                    limit=1.0),
            SloSpec("explained_bit", path="annotations.annotated", op=">=",
                    limit=1),
            SloSpec("slot_accounting_exact", path="explain_accounting_exact",
                    op="==", limit=True),
            SloSpec("explain_p99_ms", path="explain.latency_ms.p99",
                    op="<=", limit=60000.0),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _campaign_explain_paged(seed: int, scale: float) -> GameDay:
    # Pool arithmetic at the paged lane's geometry (page_size 64,
    # prompt_width 448, 12 new tokens → max_len 460, 8 view pages; the
    # ~293-token shared preamble is 5 pages, 4 of them full): each admit
    # needs 4 fresh pages, so 5 + 4*8 = 37 pages serves all 8 slots with
    # zero pool drops — while a 37-page budget would fit only FOUR
    # contiguous 8-page slots. Coverage == 1.0 at a slot count the
    # unpaged cache cannot afford is the point of this scenario.
    return GameDay(
        name="campaign_explain_paged",
        description="The campaign_explain wave on the PAGED slotserve "
                    "lane: the shared explain preamble is prefilled once "
                    "into refcounted pages, every admit copy-on-writes "
                    "the partial prefix page and allocates only suffix "
                    "pages, and the pool is capped where a contiguous "
                    "cache could not fit the slot count — coverage must "
                    "still be exactly 1.0 with exact page accounting.",
        seed=seed,
        traffic=(
            SteadyLoad(name="baseline", rate=100 * scale, duration_s=2.5,
                       scam_fraction=0.15),
            CampaignWave(name="campaign", at_s=0.5, duration_s=1.8,
                         wave_rate=400 * scale, waves=2, wave_s=0.5,
                         gap_s=0.4),
        ),
        explain_slots=8,
        explain_queue=48,
        explain_tokens=12,
        explain_paged=True,
        explain_kv_pages=37,
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            SloSpec("explain_coverage", path="explain_coverage", op="==",
                    limit=1.0),
            SloSpec("explained_bit", path="annotations.annotated", op=">=",
                    limit=1),
            SloSpec("slot_accounting_exact", path="explain_accounting_exact",
                    op="==", limit=True),
            # The paged gates: the preamble must actually be shared (a
            # prefix hit per admitted request), the pool must hold the
            # declared cap, and the lane must report real HBM savings
            # against the contiguous layout at the same slot count.
            SloSpec("prefix_shared", path="explain.prefix_hits", op=">=",
                    limit=1),
            SloSpec("paged_pool_capped", path="explain.kv_pages", op="==",
                    limit=37),
            SloSpec("hbm_saved", path="explain.kv_bytes_saved_vs_contiguous",
                    op=">", limit=0),
            SloSpec("explain_p99_ms", path="explain.latency_ms.p99",
                    op="<=", limit=60000.0),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _drift_shift(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="drift_shift",
        description="THE closed-loop game day: a novel-vocabulary fraud "
                    "campaign the live model scores benign hits mid-run; "
                    "delayed ground-truth labels join the learn window, "
                    "the drift trigger fires a warm-started retrain, the "
                    "candidate publishes, shadow-scores, and "
                    "auto-promotes through the PSI/agreement/health "
                    "gates — with exact join accounting and "
                    "zero-loss/zero-dup through the hot swap.",
        seed=seed,
        model="xgb",
        batch_size=128,
        traffic=(
            SteadyLoad(name="baseline", rate=140 * scale, duration_s=4.0,
                       scam_fraction=0.15, emit_truth=True),
            DriftCampaign(name="drift", at_s=1.0, duration_s=3.0,
                          wave_rate=500 * scale, waves=2, wave_s=0.8,
                          gap_s=0.4),
        ),
        learn=LearnSpec(min_labeled=96, min_new_labels=24,
                        error_threshold=0.12, error_window=256,
                        refresh_rounds=6, label_delay_s=0.2,
                        drift_at_s=1.0, promote_within_s=60.0),
        # Drift becomes an INCIDENT through the shadow lane: once the
        # drift-corrected candidate stages, its disagreement with the
        # drifted primary burns both sentinel windows.
        sentinel=SentinelSpec(expect=(
            ExpectedDetection("shadow_disagreement_burn", fault_at_s=1.0,
                              within_s=60.0),)),
        # The learn-evidence gates are scope="gameday": only the full
        # game-day runner wires the label oracle + learn lane (a bare
        # `serve --scenario drift_shift` replays the traffic shape and
        # honestly skips them).
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            # Drift was REAL: the primary's label-error rate on the
            # joined window shows the live model was wrong about recent
            # ground truth.
            SloSpec("drift_was_real",
                    path="learn.primary_window_error_rate", op=">=",
                    limit=0.08, scope="gameday"),
            SloSpec("retrain_published", path="learn.published", op=">=",
                    limit=1, scope="gameday"),
            SloSpec("auto_promoted", path="learn.promoted", op=">=",
                    limit=1, scope="gameday"),
            SloSpec("promotion_within_s",
                    path="learn_promotion_latency_s", op="<=",
                    limit=60.0, scope="gameday"),
            # Exact label-join accounting: joined + expired + missed +
            # pending == labels_seen, and labels actually joined.
            SloSpec("join_accounting_exact",
                    path="learn.window.accounting_exact", op="==",
                    limit=True, scope="gameday"),
            SloSpec("labels_joined_bit", path="learn.window.joined",
                    op=">=", limit=1, scope="gameday"),
            # Post-promotion agreement recovery: the promoted candidate
            # agrees with ground truth on the very window the primary
            # failed (its label-error rate collapses).
            SloSpec("agreement_recovery",
                    path="learn.candidate_window_error_rate", op="<=",
                    limit=0.1, scope="gameday"),
            # The promotion landed as a zero-downtime swap, fully audited.
            SloSpec("hot_swap_landed", path="swaps", op=">=", limit=1,
                    scope="gameday"),
            SloSpec("lifecycle_audited", path="lifecycle.audit_ok",
                    op="==", limit=True, scope="gameday"),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _chaos_storm(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="chaos_storm",
        description="Full-vocabulary broker chaos (transport errors, "
                    "lossy flushes, fences, duplicates, corruption) under "
                    "a campaign: the supervisor must converge with zero "
                    "LOST rows (at-least-once duplicates are the "
                    "documented semantics).",
        seed=seed,
        supervise=40,
        chaos=ChaosSpec(poll_error_rate=0.05, latency_spike_rate=0.04,
                        duplicate_rate=0.05, corrupt_rate=0.03,
                        flush_fail_rate=0.05, flush_crash_rate=0.04,
                        commit_fence_rate=0.04, max_faults=40),
        dlq=True,
        # Transport chaos kills incarnations from t=0 (poll errors, flush
        # crashes): the restart-churn rule — judged through the
        # chain-cumulative source — must see the crash loop. (Corruption
        # would also DLQ rows, but corrupt draws are per-poll and can be
        # zero at small scales; the restart chain is the guaranteed
        # manifestation.) The bound is wide because supervised backoff
        # chains stretch the drain.
        sentinel=SentinelSpec(expect=(
            ExpectedDetection("restart_churn", fault_at_s=0.0,
                              within_s=25.0),)),
        traffic=(
            SteadyLoad(name="baseline", rate=180 * scale, duration_s=3.0,
                       scam_fraction=0.2),
            CampaignWave(name="campaign", at_s=1.0, duration_s=1.8,
                         wave_rate=500 * scale, waves=1, wave_s=0.8,
                         gap_s=0.4),
        ),
        slos=(
            SloSpec("zero_loss", kind="zero_loss"),
            SloSpec("chaos_bit", path="chaos.total", op=">=", limit=1,
                    scope="gameday"),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _diurnal_hotkey(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="diurnal_hotkey",
        description="A diurnal tide with heavy hot-key/regional skew and "
                    "no faults: the clean-path control arm — exact "
                    "accounting and bounded batch latency under a "
                    "realistic, partition-skewed curve.",
        seed=seed,
        traffic=(DiurnalLoad(name="tide", duration_s=4.0,
                             base_rate=80 * scale, peak_rate=400 * scale,
                             period_s=4.0, scam_fraction=0.25,
                             hot_fraction=0.5, hot_keys=3),),
        # The false-positive gate: the FULL default rule pack runs on the
        # clean control arm and must produce ZERO incidents.
        sentinel=SentinelSpec(zero_incidents=True),
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            SloSpec("p99_batch_s", path="stats.p99_batch_latency_sec",
                    op="<=", limit=30.0),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _autoscale_rules(*, backlog_limit: float, idle_limit: float,
                     idle_for_s: float, fast_s: float = 1.0):
    """The fleet pack tuned for elastic game days: tight burn/idle
    windows (decisions are judged in seconds, not hours), the stale
    window kept short of any interregnum, and the flap watchdog at its
    default 3-events-per-window budget."""
    from fraud_detection_tpu.obs.sentinel import fleet_rule_pack

    return fleet_rule_pack(backlog_limit=backlog_limit, fast_s=fast_s,
                           slow_s=4.0, resolve_s=0.5, stale_s=2.0,
                           idle_limit=idle_limit, idle_for_s=idle_for_s)


def _diurnal_tide_scale(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="diurnal_tide_scale",
        description="The elastic tide: a paced diurnal curve whose crest "
                    "outruns two workers — the autoscaler must grow the "
                    "fleet on the watermark burn and hand the extra "
                    "worker back on the trough through the voluntary-"
                    "leave revoke barrier, with exact accounting, "
                    "bounded churn, and bounded reaction latency in "
                    "virtual seconds.",
        seed=seed,
        workers=2,
        partitions=4,
        batch_size=64,
        time_scale=1.0,
        idle_timeout=2.5,
        # One full cosine period: trough -> crest (t = 4) -> trough. The
        # crest rate is far past what two workers drain, the trough is
        # near-idle; the surge onset for reaction latency is the upslope
        # midpoint where the rate crosses the fleet's static capacity.
        traffic=(DiurnalLoad(name="tide", duration_s=8.0,
                             base_rate=30 * scale, peak_rate=2000 * scale,
                             period_s=8.0, scam_fraction=0.15),),
        autoscale=AutoscaleSpec(min_workers=2, max_workers=3,
                                cooldown_s=1.5, out_for_s=0.2,
                                in_for_s=0.3, surge_at_s=2.0),
        sentinel=SentinelSpec(
            rules=_autoscale_rules(backlog_limit=120.0, idle_limit=100.0,
                                   idle_for_s=0.4),
            expect=(ExpectedDetection("fleet_watermark_burn",
                                      fault_at_s=2.0, within_s=20.0),)),
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            # THE gates this scenario exists for: the fleet breathed out
            # on the crest and back in on the trough...
            SloSpec("scaled_out", path="autoscale.scale_outs", op=">=",
                    limit=1),
            SloSpec("scaled_in", path="autoscale.scale_ins", op=">=",
                    limit=1),
            # ...without oscillating (the autoscale_flap budget is 3
            # events per window; one tide cycle must stay well under it).
            SloSpec("bounded_churn_out", path="autoscale.scale_outs",
                    op="<=", limit=2),
            SloSpec("bounded_churn_in", path="autoscale.scale_ins",
                    op="<=", limit=2),
            SloSpec("reaction_bounded_s", path="autoscale_reaction_s",
                    op="<=", limit=15.0),
            SloSpec("p99_batch_s", path="stats.p99_batch_latency_sec",
                    op="<=", limit=30.0),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _flash_crowd_scale(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="flash_crowd_scale",
        description="The elastic flash crowd: the 20x ramp lands on TWO "
                    "workers behind the globally-coordinated adaptive "
                    "shed — scale-out must outrun shed-budget erosion "
                    "(the fleet grows toward max instead of shedding "
                    "through the spike), every shed row still an "
                    "accounted DLQ record.",
        seed=seed,
        workers=2,
        partitions=4,
        batch_size=64,
        time_scale=1.0,
        idle_timeout=2.5,
        traffic=(FlashCrowd(name="crowd", duration_s=4.5,
                            scam_fraction=0.2, base_rate=100 * scale,
                            peak_rate=2400 * scale, ramp_at_s=0.8,
                            ramp_s=0.5, hold_s=1.5, decay_s=0.5),),
        sched=_sched_config(max_queue=800, shed_policy="adaptive",
                            target_p99_ms=4000.0),
        dlq=True,
        autoscale=AutoscaleSpec(min_workers=2, max_workers=4,
                                cooldown_s=0.5, out_for_s=0.1,
                                in_for_s=2.0, surge_at_s=0.8),
        sentinel=SentinelSpec(
            rules=_autoscale_rules(backlog_limit=150.0, idle_limit=50.0,
                                   idle_for_s=1.0),
            expect=(ExpectedDetection("fleet_watermark_burn",
                                      fault_at_s=0.8, within_s=15.0),)),
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            SloSpec("scaled_out", path="autoscale.scale_outs", op=">=",
                    limit=1),
            SloSpec("reaction_bounded_s", path="autoscale_reaction_s",
                    op="<=", limit=10.0),
            # The elastic shed budget: the single-engine flash_crowd
            # tolerates 0.9 shed fraction; with capacity arriving
            # mid-ramp the crowd must mostly be SERVED, not shed.
            SloSpec("shed_budget", path="shed_fraction", op="<=",
                    limit=0.5),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


def _elastic_control(seed: int, scale: float) -> GameDay:
    return GameDay(
        name="elastic_control",
        description="The elastic control arm: a clean steady load with "
                    "the autoscaler ARMED but every signal quiet (the "
                    "burn threshold unreachable, the idle rule gated "
                    "off) — the fleet must not scale, not replace, not "
                    "flap, and the full fleet pack must end with zero "
                    "incidents.",
        seed=seed,
        workers=2,
        partitions=4,
        traffic=(SteadyLoad(name="steady", rate=150 * scale,
                            duration_s=3.0, scam_fraction=0.1),),
        autoscale=AutoscaleSpec(min_workers=2, max_workers=3,
                                cooldown_s=0.5),
        # idle_limit=0 gates fleet_idle structurally (backlog can never
        # be < 0): the false-positive arm proves no-signal -> no-action,
        # not that idleness is absent. The burn limit sits far above
        # anything a warp-fed steady load enqueues.
        sentinel=SentinelSpec(
            rules=_autoscale_rules(backlog_limit=50000.0, idle_limit=0.0,
                                   idle_for_s=1.0, fast_s=8.0),
            zero_incidents=True),
        slos=(
            SloSpec("exact_accounting", kind="exact_accounting"),
            SloSpec("no_scale_out", path="autoscale.scale_outs", op="==",
                    limit=0),
            SloSpec("no_scale_in", path="autoscale.scale_ins", op="==",
                    limit=0),
            SloSpec("no_replace", path="autoscale.replacements", op="==",
                    limit=0),
            SloSpec("spans_exact", kind="spans_exact"),
            SloSpec("no_errors", kind="no_errors"),
        ))


CATALOG: dict = {
    "flash_crowd": _flash_crowd,
    "campaign_breaker": _campaign_breaker,
    "campaign_explain": _campaign_explain,
    "campaign_explain_paged": _campaign_explain_paged,
    "campaign_kill_swap": _campaign_kill_swap,
    "chaos_storm": _chaos_storm,
    "coordinator_kill": _coordinator_kill,
    "diurnal_hotkey": _diurnal_hotkey,
    "diurnal_tide_scale": _diurnal_tide_scale,
    "drift_shift": _drift_shift,
    "elastic_control": _elastic_control,
    "flash_crowd_scale": _flash_crowd_scale,
}


def get_scenario(name: str, seed: int = 0, *, scale: float = 1.0) -> GameDay:
    """Look up a catalog scenario; ``scale`` multiplies every traffic
    rate (CI/bench run scale < 1 for speed, soaks scale > 1)."""
    factory = CATALOG.get(name)
    if factory is None:
        raise KeyError(
            f"unknown scenario {name!r}; catalog: {sorted(CATALOG)}")
    if scale <= 0:
        raise ValueError(f"scale must be > 0, got {scale}")
    return factory(seed, scale)


def parse_scenario_ref(ref: str) -> Tuple[str, int]:
    """``NAME[:seed]`` → (name, seed) — the serve --scenario syntax."""
    name, _, seed_raw = ref.partition(":")
    if not seed_raw:
        return name, 0
    try:
        return name, int(seed_raw)
    except ValueError:
        raise ValueError(f"bad scenario ref {ref!r}: seed must be an int")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Run a named game-day scenario against an in-process "
                    "serving stack and gate on its SLOs "
                    "(docs/scenarios.md). Exit 0 = verdict PASS, "
                    "1 = an SLO failed.")
    ap.add_argument("--name", default=None,
                    help=f"catalog scenario ({', '.join(sorted(CATALOG))})")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--scale", type=float, default=1.0,
                    help="traffic-rate multiplier (CI smokes run < 1)")
    ap.add_argument("--time-scale", type=float, default=0.0,
                    help="0 = warp (default), 1 = real-time pacing")
    ap.add_argument("--slo", action="append", default=[], metavar="EXPR",
                    help="extra gate, e.g. 'stats.p99_batch_latency_sec"
                         "<=0.5' or a builtin name; repeatable")
    ap.add_argument("--learn-policy", default=None, metavar="SPEC",
                    help="override a learn scenario's PromotionPolicy "
                         "spec (registry/promote.py parse syntax) — the "
                         "CI learn-smoke proves an impossible policy "
                         "REFUSES promotion and fails the gate")
    ap.add_argument("--json", action="store_true",
                    help="print only the machine-readable verdict line")
    ap.add_argument("--record", default=None, metavar="PATH",
                    help="persist the run's control-lane journal (plus "
                         "its conformance verdict) as a JSON recording "
                         "`flightcheck conform --input PATH` can replay")
    ap.add_argument("--list", action="store_true",
                    help="list catalog scenarios and exit")
    args = ap.parse_args(argv)
    if args.list:
        for name in sorted(CATALOG):
            gd = CATALOG[name](0, 1.0)
            print(f"{name:22s} {gd.description}")
        return 0
    if args.name is None:
        ap.error("--name is required (or --list)")
    try:
        extra = tuple(parse_slo(e) for e in args.slo)
        gd = get_scenario(args.name, args.seed, scale=args.scale)
        if args.learn_policy is not None:
            if gd.learn is None:
                raise ValueError(
                    f"--learn-policy: scenario {args.name!r} declares no "
                    "learn loop")
            import dataclasses

            from fraud_detection_tpu.registry import PromotionPolicy

            PromotionPolicy.parse(args.learn_policy)   # validate early
            gd = dataclasses.replace(
                gd, learn=dataclasses.replace(gd.learn,
                                              policy=args.learn_policy))
    except (KeyError, ValueError) as e:
        raise SystemExit(str(e))
    result = run_gameday(gd, time_scale=args.time_scale, extra_slos=extra,
                         record_path=args.record)
    if not args.json:
        print(result.table())
    print(json.dumps(result.as_dict()))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
