"""LabelFeeder: the scenario harness's delayed ground-truth oracle.

A drift game day (docs/online_learning.md, scenarios/gameday.py
``drift_shift``) needs the label lane fed: every input row's ground truth,
delivered as a FEEDBACK record keyed by the row's real source coordinate
(topic, partition, offset). Coordinates are assigned by the broker at
produce time, so the oracle cannot ride the traffic generator — instead it
CONSUMES the input topic through its own consumer group (observing exactly
the coordinates the serving engine sees), reads each payload's ``truth``
field (emitted by specs with ``emit_truth=True``, scenarios/traffic.py),
and produces one ``stream/feedback.py`` label record per truth-carrying
row. ``delay_s`` models label latency in virtual seconds (chargebacks
arrive late): labels are held back until the scenario clock passes
``row poll time + delay_s`` — in warp mode that's immediate, exactly like
every other virtual-time component.

One daemon thread per run ("label-feeder", registered in
analysis/entrypoints.py); counters under a small lock; rows without a
``truth`` field are counted and skipped (the oracle never guesses)."""

from __future__ import annotations

import json
import threading
from typing import Optional

from fraud_detection_tpu.stream.feedback import label_record


class LabelFeeder:
    """See module docstring. ``consumer`` reads the input topic (own
    group); ``producer`` writes ``feedback_topic``; ``clock`` is the
    scenario clock (pacing + virtual stamps)."""

    def __init__(self, consumer, producer, feedback_topic: str, *,
                 clock=None, delay_s: float = 0.0,
                 poll_timeout_s: float = 0.02):
        self._consumer = consumer
        self._producer = producer
        self.feedback_topic = feedback_topic
        self._clock = clock
        self.delay_s = delay_s
        self._poll_timeout = poll_timeout_s
        self._lock = threading.Lock()
        self._fed = 0
        self._skipped = 0
        self._malformed = 0
        # Drain-side virtual cursor (the VirtualCadence pattern,
        # obs/sentinel/engine.py): the scenario clock's cursor STOPS at
        # the timeline's end, so a label stamped ``end + delay_s`` would
        # never come due in warp mode — idle oracle ticks advance the
        # reading one small virtual step each instead, exactly like
        # sentinel evaluations during a warp drain.
        self._vcursor = 0.0
        self._idle_step = max(delay_s / 4.0, 0.01)
        self._stop = threading.Event()
        self.error: Optional[BaseException] = None  # write-once latch
        self._thread: Optional[threading.Thread] = None

    # -- cross-thread surface -------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {"fed": self._fed, "skipped": self._skipped,
                    "malformed": self._malformed}

    @property
    def fed(self) -> int:
        with self._lock:
            return self._fed

    def start(self) -> "LabelFeeder":
        t = threading.Thread(target=self._run, name="label-feeder",
                             daemon=True)
        with self._lock:
            self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            self._stop.set()
            t.join(timeout)

    # -- feeder thread --------------------------------------------------

    def _run(self) -> None:
        try:
            pending = []   # (due_virtual_s, topic, partition, offset, truth)
            while not self._stop.is_set():
                msgs = self._consumer.poll_batch(256, self._poll_timeout)
                now = self._clock.now() if self._clock is not None else 0.0
                for m in msgs:
                    truth = self._truth_of(m.value)
                    if truth is None:
                        continue
                    pending.append((now + self.delay_s, m.topic,
                                    m.partition, m.offset, truth))
                if msgs:
                    offsets: dict = {}
                    for m in msgs:
                        offsets[(m.topic, m.partition)] = max(
                            offsets.get((m.topic, m.partition), 0),
                            m.offset + 1)
                    self._consumer.commit_offsets(offsets)
                now = self._clock.now() if self._clock is not None else 0.0
                if msgs:
                    self._vcursor = max(self._vcursor, now)
                else:
                    # Idle tick: advance the drain-side virtual cursor so
                    # held labels come due after a warp feed (see ctor).
                    self._vcursor = max(now, self._vcursor + self._idle_step)
                now = max(now, self._vcursor)
                due = [p for p in pending if p[0] <= now]
                if due:
                    pending = [p for p in pending if p[0] > now]
                    for _, topic, partition, offset, truth in due:
                        self._producer.produce(
                            self.feedback_topic,
                            label_record(topic, partition, offset, truth))
                    flush = getattr(self._producer, "flush", None)
                    if flush is not None:
                        flush()
                    with self._lock:
                        self._fed += len(due)
                if not msgs and not due:
                    self._stop.wait(0.005)
        except BaseException as e:  # noqa: BLE001 — surfaced via .error
            self.error = e

    def _truth_of(self, value: bytes) -> Optional[int]:
        try:
            obj = json.loads(value)
        except ValueError:
            with self._lock:
                self._malformed += 1
            return None
        truth = obj.get("truth") if isinstance(obj, dict) else None
        if isinstance(truth, bool) or not isinstance(truth, int):
            with self._lock:
                self._skipped += 1
            return None
        return truth
