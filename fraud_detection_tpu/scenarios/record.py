"""Trace recordings: serialize a RowTracer's SpanRing to replayable JSONL.

A production-shaped run becomes a regression input: ``serve
--trace-record FILE`` runs the tracer in **record mode** (sample forced to
1.0, plus one compact ``row`` event block per delivered batch carrying
every row's source coordinates — obs/trace.py ``record_rows``) and dumps
the ring at exit through the shared atomic writer, so the file on disk is
never torn. ``scenarios/replay.py`` turns the file back into traffic with
the original inter-batch timing (or time-warped).

Format — one JSON object per line:

* line 1, the header::

      {"format": "fraud_tpu_trace", "version": 1, "worker": "w0",
       "time": <wall>, "complete": true|false, "snapshot": {<trace block>}}

  ``complete`` is the replayability claim: record mode was on, nothing was
  head-sampled away, and the ring dropped zero spans. Replay REFUSES an
  incomplete recording unless forced — a recording with holes would
  silently replay a smaller run and call it regression coverage.
* every further line: one span, exactly ``Span.as_dict()`` —
  ``{"cid", "stage", "start", "duration_ms", "ok", "detail"}``. Row-level
  lines carry the row cid ``<batch>:<partition>:<offset>``; the
  coordinates ARE the row identity (the same coordinates DLQ records
  carry), which is what lets replay reproduce the exact row set without
  recording payload bytes.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from fraud_detection_tpu.utils.atomicio import atomic_write_text

FORMAT = "fraud_tpu_trace"
VERSION = 1

# Stages whose cids are ROW cids (<batch>:<partition>:<offset>); the union
# of their coordinates is the recording's row census.
ROW_STAGES = ("row", "shed", "dlq", "flag")


def render_recording(tracer, *, now: Optional[float] = None) -> str:
    """The JSONL text of ``tracer``'s current ring (header + spans)."""
    snapshot = tracer.snapshot()
    spans = tracer.ring.snapshot()
    complete = (bool(getattr(tracer, "record_rows", False))
                and snapshot["sample"] >= 1.0
                and snapshot["ring_dropped"] == 0)
    header = {
        "format": FORMAT,
        "version": VERSION,
        "worker": snapshot["worker"],
        "time": now,
        "complete": complete,
        "snapshot": snapshot,
    }
    lines = [json.dumps(header)]
    lines.extend(json.dumps(s.as_dict()) for s in spans)
    return "\n".join(lines) + "\n"


def dump_tracer(tracer, path: str, *, now: Optional[float] = None) -> dict:
    """Atomically publish ``tracer``'s recording at ``path``; returns the
    header (with ``spans`` count added) for the caller's exit report.
    Raises OSError-shaped failures as a plain RuntimeError — a requested
    recording that silently vanished would be worse than a loud exit."""
    text = render_recording(tracer, now=now)
    if not atomic_write_text(path, text):
        raise RuntimeError(f"could not write trace recording to {path!r}")
    header = json.loads(text.split("\n", 1)[0])
    header["spans"] = text.count("\n") - 1
    return header


def load_recording(path: str) -> Tuple[dict, List[dict]]:
    """Parse a recording file -> (header, span dicts). Validates the
    format marker; raises ValueError on anything unrecognizable."""
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path!r} is empty — not a trace recording")
    header = json.loads(lines[0])
    if header.get("format") != FORMAT:
        raise ValueError(
            f"{path!r} is not a {FORMAT} recording "
            f"(format={header.get('format')!r})")
    if header.get("version") != VERSION:
        raise ValueError(
            f"{path!r} has recording version {header.get('version')!r}; "
            f"this build reads version {VERSION}")
    return header, [json.loads(ln) for ln in lines[1:]]


def row_coordinate(cid: str) -> Optional[Tuple[int, int]]:
    """(partition, offset) of a ROW cid; None for batch cids."""
    parts = cid.split(":")
    if len(parts) != 3:
        return None
    try:
        return int(parts[1]), int(parts[2])
    except ValueError:
        return None


def recording_rows(spans: List[dict]) -> List[Tuple[int, int]]:
    """The recording's row census: every distinct (partition, offset)
    seen on a row-stage span, sorted."""
    coords = set()
    for s in spans:
        if s.get("stage") in ROW_STAGES:
            c = row_coordinate(s.get("cid", ""))
            if c is not None:
                coords.add(c)
    return sorted(coords)


def batch_schedule(spans: List[dict]) -> List[dict]:
    """Per-batch replay schedule, in original start order. Each entry:
    ``{"cid", "start", "rows": [(p, o), ...], "flagged": {(p, o), ...}}``.
    Rows attach to their batch through the cid prefix; batches whose poll
    span was dropped (incomplete recordings) still appear, ordered by
    their earliest span."""
    batches: Dict[str, dict] = {}

    def entry(batch_cid: str) -> dict:
        b = batches.get(batch_cid)
        if b is None:
            b = batches[batch_cid] = {"cid": batch_cid, "start": None,
                                      "rows": set(), "flagged": set()}
        return b

    for s in spans:
        cid = s.get("cid", "")
        batch_cid = cid.split(":", 1)[0]
        b = entry(batch_cid)
        start = s.get("start")
        if start is not None and (b["start"] is None or start < b["start"]):
            b["start"] = start
        if s.get("stage") in ROW_STAGES:
            c = row_coordinate(cid)
            if c is not None:
                b["rows"].add(c)
                if s["stage"] == "flag":
                    b["flagged"].add(c)
    out = [b for b in batches.values() if b["rows"]]
    out.sort(key=lambda b: (b["start"] if b["start"] is not None else 0.0,
                            b["cid"]))
    for b in out:
        b["rows"] = sorted(b["rows"])
        b["flagged"] = set(b["flagged"])
    return out
