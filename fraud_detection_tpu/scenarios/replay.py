"""Recorded-trace replay: turn a trace recording back into traffic.

A recording (scenarios/record.py) carries, per delivered batch, its start
time and every row's source coordinates — enough to regenerate the run as
a load shape: the same batches, the same row counts, the same inter-batch
gaps (or time-warped through ``time_scale``), and the same flagged-row mix
(rows the original run flagged replay with scam-family text, so the
explain/annotation lanes see the same pressure). Each replayed row is
keyed by its original source coordinate ``<partition>:<offset>`` — the
row's identity in the recording — so after the replay run drains, the
output key multiset must equal the recording's row census EXACTLY
(zero-loss accounting through the whole pipeline, pinned in
tests/test_scenarios.py and surfaced by the CLI's exit code).

CLI::

    python -m fraud_detection_tpu.scenarios.replay recording.jsonl \
        [--time-scale 0.0] [--batch-size 1024] [--force]

exits 0 when the replayed key set reproduces the recording exactly,
1 otherwise. ``--time-scale 0`` (default) is warp mode: the schedule
replays as fast as the engine drains it; 1.0 replays the original pacing.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional, Tuple

from fraud_detection_tpu.scenarios.clock import ScenarioClock, derive_seed
from fraud_detection_tpu.scenarios.record import (batch_schedule,
                                                  load_recording,
                                                  recording_rows)
from fraud_detection_tpu.scenarios.traffic import TrafficEvent, _text_pools


def coordinate_key(coord: Tuple[int, int]) -> bytes:
    """The replayed row's broker key — its recorded source coordinate."""
    return f"{coord[0]}:{coord[1]}".encode()


def replay_events(header: dict, spans: List[dict], *,
                  seed: int = 0) -> List[TrafficEvent]:
    """Synthesize the recording's traffic timeline. Deterministic for a
    given (recording, seed): replayed payload text derives from the row's
    coordinates, not from any call-order rng."""
    schedule = batch_schedule(spans)
    legit_pool, scam_pool = _text_pools(derive_seed(seed, "replay-texts"))
    t0 = min((b["start"] for b in schedule if b["start"] is not None),
             default=0.0)
    events: List[TrafficEvent] = []
    seen = set()    # a chaos-replayed row can appear in an aborted batch
                    # AND its re-drive — replay each coordinate ONCE, at
                    # its first appearance
    for b in schedule:
        t = max(0.0, (b["start"] or t0) - t0)
        for p, o in b["rows"]:
            if (p, o) in seen:
                continue
            seen.add((p, o))
            flagged = (p, o) in b["flagged"]
            pool = scam_pool if flagged else legit_pool
            text = pool[(p * 8191 + o) % len(pool)]
            value = json.dumps(
                {"text": text, "id": f"{p}:{o}",
                 "replay": header.get("worker", "w0")},
                sort_keys=True).encode()
            events.append(TrafficEvent(round(t, 6), value,
                                       coordinate_key((p, o)),
                                       "scam" if flagged else "legit"))
    return events


def run_replay(recording_path: str, pipeline, *, time_scale: float = 0.0,
               batch_size: int = 1024, force: bool = False,
               seed: int = 0) -> dict:
    """Replay a recording against a fresh in-process engine; returns the
    machine-readable report (``keys_exact`` is the regression verdict)."""
    from fraud_detection_tpu.stream import InProcessBroker, StreamingClassifier

    header, spans = load_recording(recording_path)
    if not header.get("complete") and not force:
        raise ValueError(
            f"{recording_path!r} is not a complete recording (record mode "
            f"off, sampled, or ring overflowed: "
            f"dropped={header.get('snapshot', {}).get('ring_dropped')}) — "
            "an exact replay is impossible; pass force=True to replay the "
            "surviving subset anyway")
    events = replay_events(header, spans, seed=seed)
    coords = recording_rows(spans)
    expected = sorted(coordinate_key(c) for c in coords)

    clock = ScenarioClock(seed, time_scale=time_scale)
    max_part = max((p for p, _ in coords), default=2)
    broker = InProcessBroker(num_partitions=max(3, max_part + 1))
    from fraud_detection_tpu.scenarios.traffic import TrafficFeeder

    feeder = TrafficFeeder(broker.producer(), "replay-in", events, clock)
    engine = StreamingClassifier(
        pipeline, broker.consumer(["replay-in"], "replay"),
        broker.producer(), "replay-out", batch_size=batch_size,
        max_wait=0.02)
    # The engine must outlast the replay's longest quiet stretch, or a
    # paced replay of a bursty recording would idle-exit mid-schedule.
    gaps = [b - a for a, b in zip([e.t for e in events],
                                  [e.t for e in events][1:])]
    idle = max(5.0, 2.0 * time_scale * max(gaps, default=0.0))
    t0 = time.perf_counter()
    feeder.start()
    stats = engine.run(max_messages=len(events), idle_timeout=idle)
    feeder.join(timeout=60.0)
    engine.consumer.close()
    wall = time.perf_counter() - t0
    if feeder.error is not None:
        raise feeder.error

    got = sorted(m.key for m in broker.messages("replay-out"))
    missing = len(set(expected) - set(got))
    extra = len(got) - len(set(got) & set(expected))
    return {
        "recording": {"path": recording_path,
                      "worker": header.get("worker"),
                      "complete": bool(header.get("complete")),
                      "spans": len(spans)},
        "rows": len(coords),
        "batches": len(batch_schedule(spans)),
        "fed": feeder.fed,
        "keys_exact": got == expected,
        "missing": missing,
        "duplicated_or_extra": extra,
        "time_scale": time_scale,
        "wall_s": round(wall, 3),
        "stats": stats.as_dict(),
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Replay a serve --trace-record recording against a "
                    "fresh in-process engine and verify the row key set "
                    "reproduces exactly (docs/scenarios.md).")
    ap.add_argument("recording", help="JSONL recording path "
                                      "(serve --trace-record FILE)")
    ap.add_argument("--time-scale", type=float, default=0.0,
                    help="0 = warp (as fast as the engine drains; "
                         "default), 1.0 = original pacing, 0.5 = 2x speed")
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--force", action="store_true",
                    help="replay an INCOMPLETE recording's surviving "
                         "subset (keys_exact then covers the subset only)")
    ap.add_argument("--seed", type=int, default=0,
                    help="seed for the synthesized payload texts")
    args = ap.parse_args(argv)
    if args.time_scale < 0:
        raise SystemExit(f"--time-scale must be >= 0, got {args.time_scale}")
    if args.batch_size < 1:
        raise SystemExit(f"--batch-size must be >= 1, got {args.batch_size}")

    from fraud_detection_tpu.models.pipeline import synthetic_demo_pipeline

    pipeline = synthetic_demo_pipeline(args.batch_size)
    try:
        report = run_replay(args.recording, pipeline,
                            time_scale=args.time_scale,
                            batch_size=args.batch_size, force=args.force,
                            seed=args.seed)
    except (ValueError, OSError) as e:
        raise SystemExit(str(e))
    print(json.dumps(report))
    return 0 if report["keys_exact"] else 1


if __name__ == "__main__":
    sys.exit(main())
