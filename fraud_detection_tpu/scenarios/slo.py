"""Game-day SLO gates: declarative pass/fail assertions over run evidence.

A scenario run (scenarios/gameday.py, serve ``--scenario``) collects one
**evidence** dict — broker key multisets, merged StreamStats, final
health/trace/breaker/sched blocks, fault reports — and the scenario's SLOs
are data evaluated against it, not asserts buried in a script. Two kinds:

* **Builtins** (``kind`` names a check with real logic):

  - ``zero_loss`` — every fed key appears among the accounted outputs
    (classified + DLQ'd) at least as often as it was fed. Multiset, not
    set: hot-key skew deliberately repeats keys.
  - ``zero_dup`` — no key appears MORE often than it was fed.
  - ``exact_accounting`` — both at once (the fleet's zero-loss/zero-dup
    contract); fails with the missing/duplicated counts in the detail.
  - ``spans_exact`` — every tracer finished with ``spans_open == 0`` and
    ``batches_traced == batches_closed`` (the PR 10 accounting invariant,
    asserted from the evidence's trace blocks).
  - ``no_errors`` — no worker/feeder/action errors were recorded.

* **Metric gates** (``kind="metric"``): a dotted ``path`` into the
  evidence compared against ``limit`` with ``op`` — e.g.
  ``stats.p99_batch_latency_sec <= 5`` or ``breaker.opens >= 1``. A
  missing path FAILS (evidence that silently vanished must not read as a
  pass); paths that are only meaningful in one runner mode carry
  ``scope`` so the serve CLI's single-engine evaluation skips
  fleet-only gates instead of failing them.

``evaluate`` returns an :class:`SloReport`: machine-readable
(``as_dict``), human-readable (``table`` — the game-day verdict table),
and one ``ok`` bit that becomes the process exit code.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import List, Sequence, Tuple, Union

Number = Union[int, float]

_OPS = {
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
}

BUILTIN_KINDS = ("zero_loss", "zero_dup", "exact_accounting",
                 "spans_exact", "no_errors")

#: Parameterized kinds beyond the bare builtins: ``detects_within`` judges
#: the sentinel's detection latency — ``path`` names the alert rule,
#: ``limit`` the allowed sentinel-clock seconds between the fault's
#: injection time (``evidence["fault_times"][rule]``) and the rule's first
#: FIRING incident (docs/observability.md "Detection-latency gates").
PARAM_KINDS = ("detects_within",)


@dataclass(frozen=True)
class SloSpec:
    """One declared gate. For builtins, ``kind`` is the check and
    path/op/limit are ignored; for ``kind="metric"``, ``path`` walks the
    evidence dict. ``scope`` limits where the gate is evaluable:
    ``"any"`` everywhere, ``"gameday"`` only under the full game-day
    runner (serve --scenario marks these skipped instead of failed)."""

    name: str
    kind: str = "metric"
    path: str = ""
    op: str = "<="
    limit: Union[Number, str, bool, None] = 0
    scope: str = "any"

    def __post_init__(self):
        if self.kind not in BUILTIN_KINDS and self.kind not in PARAM_KINDS \
                and self.kind != "metric":
            raise ValueError(
                f"unknown SLO kind {self.kind!r} (builtins: "
                f"{BUILTIN_KINDS}, parameterized: {PARAM_KINDS})")
        if self.kind == "detects_within":
            if not self.path:
                raise ValueError(
                    f"detects_within SLO {self.name!r} needs the alert "
                    f"rule name in 'path'")
            if not isinstance(self.limit, (int, float)) \
                    or isinstance(self.limit, bool) or self.limit <= 0:
                raise ValueError(
                    f"detects_within SLO {self.name!r} needs a positive "
                    f"numeric limit (seconds), got {self.limit!r}")
        if self.kind == "metric":
            if not self.path:
                raise ValueError(f"metric SLO {self.name!r} needs a path")
            if self.op not in _OPS:
                raise ValueError(
                    f"SLO {self.name!r}: op must be one of "
                    f"{sorted(_OPS)}, got {self.op!r}")
        if self.scope not in ("any", "gameday"):
            raise ValueError(
                f"SLO {self.name!r}: scope must be 'any' or 'gameday', "
                f"got {self.scope!r}")


def parse_slo(expr: str, *, scope: str = "any") -> SloSpec:
    """Parse a CLI override like ``stats.p99_batch_latency_sec<=0.5`` or a
    bare builtin name like ``exact_accounting``."""
    text = expr.strip()
    if text in BUILTIN_KINDS:
        return SloSpec(text, kind=text, scope=scope)
    for op in ("<=", ">=", "==", "!=", "<", ">"):   # two-char ops first
        if op in text:
            path, raw = text.split(op, 1)
            raw = raw.strip()
            value: Union[Number, str, bool, None]
            if raw.lower() in ("true", "false"):
                value = raw.lower() == "true"
            elif raw.lower() in ("none", "null"):
                value = None
            else:
                try:
                    value = int(raw)
                except ValueError:
                    try:
                        value = float(raw)
                    except ValueError:
                        value = raw
            return SloSpec(text, path=path.strip(), op=op, limit=value,
                           scope=scope)
    raise ValueError(
        f"cannot parse SLO {expr!r}: expected a builtin name "
        f"({', '.join(BUILTIN_KINDS)}) or '<path><op><value>'")


@dataclass(frozen=True)
class SloVerdict:
    name: str
    ok: bool
    observed: object
    expected: str
    detail: str = ""
    skipped: bool = False

    def as_dict(self) -> dict:
        return {"name": self.name, "ok": self.ok,
                "observed": self.observed, "expected": self.expected,
                "detail": self.detail, "skipped": self.skipped}


@dataclass
class SloReport:
    verdicts: List[SloVerdict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(v.ok or v.skipped for v in self.verdicts)

    @property
    def failed(self) -> List[SloVerdict]:
        return [v for v in self.verdicts if not v.ok and not v.skipped]

    def as_dict(self) -> dict:
        return {"ok": self.ok,
                "verdicts": [v.as_dict() for v in self.verdicts]}

    def table(self) -> str:
        """The verdict table (examples/game_day_demo.py prints this)."""
        rows = [("SLO", "observed", "expected", "verdict")]
        for v in self.verdicts:
            verdict = ("SKIP" if v.skipped else "PASS" if v.ok else "FAIL")
            rows.append((v.name, str(v.observed), v.expected, verdict))
        widths = [max(len(r[i]) for r in rows) for i in range(4)]
        lines = []
        for i, r in enumerate(rows):
            lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)))
            if i == 0:
                lines.append("  ".join("-" * w for w in widths))
        for v in self.verdicts:
            if not v.ok and not v.skipped and v.detail:
                lines.append(f"  !! {v.name}: {v.detail}")
        return "\n".join(lines)


def _resolve(evidence: dict, path: str):
    """Walk a dotted path; returns (found, value)."""
    node = evidence
    for part in path.split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        elif isinstance(node, (list, tuple)) and part.isdigit() \
                and int(part) < len(node):
            node = node[int(part)]
        else:
            return False, None
    return True, node


def _accounting(evidence: dict) -> Tuple[Counter, Counter]:
    fed = Counter(evidence.get("fed_keys") or [])
    accounted = Counter(evidence.get("out_keys") or [])
    accounted.update(evidence.get("dlq_keys") or [])
    return fed, accounted


def _check_builtin(spec: SloSpec, evidence: dict) -> SloVerdict:
    if spec.kind in ("zero_loss", "zero_dup", "exact_accounting"):
        fed, accounted = _accounting(evidence)
        missing = sum((fed - accounted).values())
        dups = sum((accounted - fed).values())
        if spec.kind == "zero_loss":
            ok, observed = missing == 0, missing
            expected = "0 lost rows"
        elif spec.kind == "zero_dup":
            ok, observed = dups == 0, dups
            expected = "0 duplicated rows"
        else:
            ok = missing == 0 and dups == 0
            observed = f"lost={missing} dup={dups}"
            expected = "lost=0 dup=0"
        sample = list((fed - accounted).keys())[:5]
        detail = (f"fed={sum(fed.values())} accounted="
                  f"{sum(accounted.values())}"
                  + (f" first_missing={sample}" if sample else ""))
        return SloVerdict(spec.name, ok, observed, expected, detail)
    if spec.kind == "spans_exact":
        traces = evidence.get("traces") or []
        if not traces:
            # A run that DECLARED tracing off (serve --scenario without
            # --trace) skips the gate honestly; a game-day run, which
            # always traces, fails it — absent evidence must not pass.
            return SloVerdict(spec.name, False, "<no trace blocks>",
                              "spans_open==0 for every tracer",
                              "tracing was not enabled for this run",
                              skipped=evidence.get("tracing") is False)
        bad = [t for t in traces
               if t.get("spans_open") != 0
               or t.get("batches_traced") != t.get("batches_closed")]
        observed = (f"{len(traces)} tracers, "
                    f"open={[t.get('spans_open') for t in bad] or 0}")
        return SloVerdict(spec.name, not bad, observed,
                          "spans_open==0, traced==closed",
                          f"bad tracers: {[t.get('worker') for t in bad]}"
                          if bad else "")
    if spec.kind == "detects_within":
        # The sentinel gate (docs/observability.md): the named alert rule
        # must have FIRED, and its first firing must land within ``limit``
        # sentinel-clock seconds of the fault's injection time. Missing
        # alerts evidence FAILS — a game day that declared a sentinel but
        # produced no alert block lost its watchdog, which is itself the
        # incident.
        rule = spec.path
        alerts = evidence.get("alerts")
        expected = f"alert {rule!r} fires within {spec.limit}s of the fault"
        if not isinstance(alerts, dict):
            return SloVerdict(spec.name, False, "<no alerts evidence>",
                              expected, "the run produced no sentinel "
                              "snapshot — was the sentinel wired?")
        incidents = [i for i in alerts.get("incidents") or []
                     if i.get("rule") == rule
                     and isinstance(i.get("fired_at"), (int, float))]
        fault_at = (evidence.get("fault_times") or {}).get(rule, 0.0)
        if not incidents:
            return SloVerdict(spec.name, False, "<never fired>", expected,
                              f"sentinel evaluated "
                              f"{alerts.get('evaluations')}x, firing="
                              f"{alerts.get('firing')}")
        fired_at = min(i["fired_at"] for i in incidents)
        latency = fired_at - fault_at
        return SloVerdict(spec.name, latency <= spec.limit,
                          round(latency, 3), expected,
                          f"fault_at={fault_at} fired_at={fired_at}")
    if spec.kind == "no_errors":
        errors = list(evidence.get("errors") or [])
        feeder = evidence.get("feeder") or {}
        errors += [f"action:{n}:{e}"
                   for n, e in feeder.get("action_errors") or []]
        return SloVerdict(spec.name, not errors, len(errors),
                          "0 worker/feeder/action errors",
                          "; ".join(str(e) for e in errors[:3]))
    raise AssertionError(spec.kind)   # unreachable: __post_init__ validated


def evaluate(slos: Sequence[SloSpec], evidence: dict, *,
             scope: str = "gameday") -> SloReport:
    """Evaluate every spec against the evidence. ``scope`` is the
    RUNNER's capability: gates scoped beyond it are reported skipped."""
    report = SloReport()
    for spec in slos:
        if spec.scope == "gameday" and scope != "gameday":
            report.verdicts.append(SloVerdict(
                spec.name, True, "<not evaluated>",
                f"scope={spec.scope}", "only evaluated by the game-day "
                "runner", skipped=True))
            continue
        if spec.kind != "metric":
            report.verdicts.append(_check_builtin(spec, evidence))
            continue
        found, value = _resolve(evidence, spec.path)
        expected = f"{spec.path} {spec.op} {spec.limit}"
        if not found:
            report.verdicts.append(SloVerdict(
                spec.name, False, "<missing>", expected,
                f"evidence has no {spec.path!r}"))
            continue
        try:
            ok = _OPS[spec.op](value, spec.limit)
        except TypeError:
            report.verdicts.append(SloVerdict(
                spec.name, False, repr(value), expected,
                f"cannot compare {type(value).__name__} with "
                f"{type(spec.limit).__name__}"))
            continue
        report.verdicts.append(SloVerdict(spec.name, bool(ok), value,
                                          expected))
    return report
