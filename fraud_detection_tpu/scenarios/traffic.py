"""Seeded traffic generation: realistic load shapes as reproducible data.

The chaos layer (stream/faults.py) injects *faults* into whatever flat load
a test happens to produce; nothing in the tree injected realistic *traffic*
— diurnal tides, flash crowds, hot-key skew, correlated fraud campaigns —
so the admission controller, the explain breaker, and the fleet's shedding
watermark were only ever judged against uniform paced batches. This module
makes traffic a first-class seeded input:

* A **spec** (:class:`SteadyLoad`, :class:`DiurnalLoad`, :class:`FlashCrowd`,
  :class:`CampaignWave`) is pure data: a rate curve over a window plus the
  mix knobs (``scam_fraction``, hot-key skew). Specs compose — a scenario
  is a list of overlapping specs (baseline diurnal + a campaign wave on
  top).
* :func:`generate` expands a spec into a flat list of
  :class:`TrafficEvent` rows — **bit-reproducible**: the same spec + seed
  yields byte-identical payloads, keys, and virtual timestamps, across
  processes (seeds derive via sha256, payload JSON is key-ordered, and the
  rate curve integrates through a deterministic accumulator, so no float
  re-association changes a row count). tests/test_scenarios.py pins this.
* :class:`TrafficFeeder` walks the merged timeline on ONE daemon thread,
  appending rows to the broker at their (scaled) virtual times and firing
  interleaved :class:`TimelineAction` callbacks (hot swaps, fault arming,
  drain-stop) at theirs — so traffic, faults, and operator actions compose
  on a single deterministic timeline (scenarios/clock.py owns the pacing
  and the per-component seed streams).

Texts come from the synthetic corpus families (data/synthetic.py) with
``hard_fraction=0``: campaign rows are *meant* to look flagged — the point
of a fraud-campaign wave is to stress every flagged-row lane (explain
breaker, annotation queue, shadow gates) at once.

Key skew: ``hot_fraction`` of rows reuse one of ``hot_keys`` literal keys.
The broker partitions by ``hash(key)``, so repeated hot keys concentrate on
few partitions — real regional/entity skew. Accounting across skewed keys
is MULTISET accounting (each input row classified exactly once), which the
SLO layer (scenarios/slo.py) implements; rows stay individually
identifiable via the ``id`` field in the payload.
"""

from __future__ import annotations

import json
import math
import threading
from dataclasses import dataclass
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

from fraud_detection_tpu.scenarios.clock import ScenarioClock, derive_seed

# Rate-curve integration step (virtual seconds). Small enough that a 2 s
# flash-crowd ramp gets ~40 distinct rate samples; rows inside a tick
# spread evenly so arrival times stay smooth at any rate.
TICK_S = 0.05


class TrafficEvent(NamedTuple):
    """One generated input row: virtual arrival time + the exact bytes."""

    t: float            # virtual seconds from scenario start
    value: bytes        # JSON payload ({"text": ..., "id": ..., ...})
    key: bytes          # broker partition key (skewed keys repeat)
    kind: str           # "legit" | "scam" (ground-truth-ish family)


@dataclass(frozen=True)
class TrafficSpec:
    """Base spec: a rate curve over ``[at_s, at_s + duration_s)``.

    ``scam_fraction`` draws each row's text family; ``hot_fraction`` routes
    that fraction of rows to one of ``hot_keys`` repeated literal keys
    (partition skew); everything else gets a unique ``<name>-<seq>`` key.
    Subclasses implement :meth:`rate_at` (rows/sec at relative time)."""

    name: str = "traffic"
    at_s: float = 0.0
    duration_s: float = 1.0
    scam_fraction: float = 0.3
    hot_fraction: float = 0.0
    hot_keys: int = 4
    # Ground-truth oracle field (docs/online_learning.md): when set, each
    # payload carries ``"truth": 0|1`` — what the scenario label feeder
    # (scenarios/labels.py) turns into delayed feedback records. OFF by
    # default so every existing spec's payload bytes are unchanged.
    emit_truth: bool = False

    def __post_init__(self):
        if self.duration_s <= 0:
            raise ValueError(f"duration_s must be > 0, got {self.duration_s}")
        if not 0.0 <= self.scam_fraction <= 1.0:
            raise ValueError(
                f"scam_fraction must be in [0, 1], got {self.scam_fraction}")
        if not 0.0 <= self.hot_fraction <= 1.0:
            raise ValueError(
                f"hot_fraction must be in [0, 1], got {self.hot_fraction}")
        if self.hot_keys < 1:
            raise ValueError(f"hot_keys must be >= 1, got {self.hot_keys}")

    def rate_at(self, rel_t: float) -> float:  # pragma: no cover - abstract
        raise NotImplementedError


@dataclass(frozen=True)
class SteadyLoad(TrafficSpec):
    """Flat offered load — the control arm every shaped curve compares to."""

    rate: float = 100.0

    def rate_at(self, rel_t: float) -> float:
        return self.rate


@dataclass(frozen=True)
class DiurnalLoad(TrafficSpec):
    """Day/night tide: a raised cosine between ``base_rate`` (trough) and
    ``peak_rate`` (crest) with period ``period_s`` — the million-user
    baseline shape (autoscaling is judged against the slope, not the
    mean)."""

    base_rate: float = 50.0
    peak_rate: float = 200.0
    period_s: float = 8.0

    def rate_at(self, rel_t: float) -> float:
        phase = (1.0 - math.cos(2.0 * math.pi * rel_t / self.period_s)) / 2.0
        return self.base_rate + (self.peak_rate - self.base_rate) * phase


@dataclass(frozen=True)
class FlashCrowd(TrafficSpec):
    """Base load that ramps to ``peak_rate`` over ``ramp_s``, holds for
    ``hold_s``, and decays back over ``decay_s`` — the admission
    controller's nemesis shape (the watermark + AIMD shed must bite on the
    ramp and RELEASE after the decay)."""

    base_rate: float = 50.0
    peak_rate: float = 2000.0
    ramp_at_s: float = 0.5
    ramp_s: float = 0.5
    hold_s: float = 1.0
    decay_s: float = 0.5

    def rate_at(self, rel_t: float) -> float:
        t = rel_t - self.ramp_at_s
        if t < 0:
            return self.base_rate
        if t < self.ramp_s:
            return self.base_rate + (self.peak_rate - self.base_rate) * (
                t / self.ramp_s)
        t -= self.ramp_s
        if t < self.hold_s:
            return self.peak_rate
        t -= self.hold_s
        if t < self.decay_s:
            return self.peak_rate + (self.base_rate - self.peak_rate) * (
                t / self.decay_s)
        return self.base_rate


@dataclass(frozen=True)
class CampaignWave(TrafficSpec):
    """Correlated fraud-campaign bursts: ``waves`` bursts of
    ``wave_rate`` rows/sec lasting ``wave_s`` each, ``gap_s`` apart,
    nearly all scam-shaped and key-skewed by default (one campaign hits
    from few origins) — the shape that stresses every flagged-row lane
    (explain breaker, annotation queue, shadow gates) at once. Overlay it
    on a baseline spec; between waves it contributes zero rows."""

    wave_rate: float = 800.0
    waves: int = 2
    wave_s: float = 0.6
    gap_s: float = 1.0
    scam_fraction: float = 0.95
    hot_fraction: float = 0.8
    hot_keys: int = 3

    def rate_at(self, rel_t: float) -> float:
        stride = self.wave_s + self.gap_s
        if rel_t >= self.waves * stride:
            return 0.0
        return self.wave_rate if (rel_t % stride) < self.wave_s else 0.0


@dataclass(frozen=True)
class DriftCampaign(TrafficSpec):
    """A NOVEL-vocabulary fraud campaign: burst shape like
    :class:`CampaignWave`, but scam rows draw from a drifted text family
    (:func:`drift_scam_pool` — crypto-wallet/airdrop templates sharing no
    scam marker with the classic phone-scam corpus the serving model
    trained on). The live model scores these benign; only the delayed
    ground-truth labels reveal them — exactly the campaign-drift shape the
    closed learning loop (learn/, docs/online_learning.md) exists to
    catch. ``emit_truth`` defaults ON: a drift scenario without its label
    oracle is undetectable by construction."""

    wave_rate: float = 400.0
    waves: int = 2
    wave_s: float = 0.8
    gap_s: float = 0.6
    scam_fraction: float = 0.9
    hot_fraction: float = 0.5
    hot_keys: int = 3
    emit_truth: bool = True

    def rate_at(self, rel_t: float) -> float:
        stride = self.wave_s + self.gap_s
        if rel_t >= self.waves * stride:
            return 0.0
        return self.wave_rate if (rel_t % stride) < self.wave_s else 0.0


# Drifted scam asks: a ROUTINE legitimate call transcript (the classic
# corpus's own legit family) with a crypto-wallet ask spliced mid-call —
# the appointment-pivot shape, drifted to a vocabulary ("wallet", "seed
# phrase", "airdrop", "staking", ...) that occurs in NEITHER classic
# family (data/synthetic.py). A model trained on the classic corpus reads
# the legit register and scores these benign; only the delayed
# ground-truth labels reveal the campaign. The loud classic markers
# (urgent/suspended/gift cards/fees/verify) are deliberately absent.
_DRIFT_ASKS = [
    "Agent: While I have you, the airdrop is ready for pickup — please "
    "connect your wallet and spell out the seed phrase so I can finish "
    "the setup.\nCustomer: Okay, let me open the wallet app now.",
    "Agent: One more thing, your staking rewards are scheduled — just "
    "share the recovery words and we will move them over for you.\n"
    "Customer: Sure, the twelve words are written on my card.",
    "Agent: Also, the nft drop closes tonight — simply approve the "
    "smart contract and tell me the passphrase while we are on the "
    "line.\nCustomer: Alright, reading the passphrase now.",
    "Agent: By the way, we migrated the exchange this week — kindly "
    "sync your hardware wallet and share the recovery words with me.\n"
    "Customer: Okay, syncing the hardware wallet now.",
    "Agent: And the validator rebate is waiting — please open the "
    "wallet app and tell me the twelve seed words so I can finish it "
    "for you.\nCustomer: One moment, opening the app.",
]


def drift_scam_pool(seed: int, n: int = 64) -> List[str]:
    """Seeded drifted-scam texts (deterministic: same seed, same pool):
    legit-family transcripts with one crypto ask spliced mid-call."""
    import random as _random

    from fraud_detection_tpu.data import generate_corpus

    rng = _random.Random(derive_seed(seed, "drift-pool"))
    corpus = generate_corpus(n=2 * n + 32,
                             seed=derive_seed(seed, "drift-base"),
                             hard_fraction=0.0, label_noise=0.0)
    legit = [d.text for d in corpus if d.label == 0]
    out = []
    for i in range(n):
        base = legit[rng.randrange(len(legit))]
        lines = base.split("\n")
        mid = max(1, len(lines) // 2)
        ask = _DRIFT_ASKS[rng.randrange(len(_DRIFT_ASKS))]
        out.append("\n".join(lines[:mid] + [ask] + lines[mid:]))
    return out


def _text_pools(seed: int) -> Tuple[List[str], List[str]]:
    """(legit, scam) text pools from the synthetic corpus families —
    separable variants (hard_fraction=0) so campaign rows actually flag."""
    from fraud_detection_tpu.data import generate_corpus

    corpus = generate_corpus(n=128, seed=seed, hard_fraction=0.0,
                             label_noise=0.0)
    legit = [d.text for d in corpus if d.label == 0]
    scam = [d.text for d in corpus if d.label == 1]
    return legit, scam


def generate(spec: TrafficSpec, seed: int) -> List[TrafficEvent]:
    """Expand one spec into its seeded event list (see module docstring
    for the determinism contract). ``seed`` should come from the scenario
    clock (``clock.derive_seed(f"traffic:{spec.name}")``) so specs never
    perturb each other's draws."""
    rng_seed = derive_seed(seed, f"spec:{spec.name}")
    import random as _random

    rng = _random.Random(rng_seed)
    legit_pool, scam_pool = _text_pools(derive_seed(rng_seed, "texts"))
    if isinstance(spec, DriftCampaign):
        # Drifted campaigns draw scam rows from the novel-vocabulary pool
        # the serving model never trained on (see DriftCampaign).
        scam_pool = drift_scam_pool(derive_seed(rng_seed, "texts"))
    events: List[TrafficEvent] = []
    acc = 0.0
    seq = 0
    n_ticks = int(math.ceil(spec.duration_s / TICK_S))
    for i in range(n_ticks):
        rel_t = i * TICK_S
        dt = min(TICK_S, spec.duration_s - rel_t)
        acc += spec.rate_at(rel_t) * dt
        n = int(acc)
        acc -= n
        for k in range(n):
            t = spec.at_s + rel_t + dt * (k + 1) / (n + 1)
            scam = rng.random() < spec.scam_fraction
            pool = scam_pool if scam else legit_pool
            text = pool[rng.randrange(len(pool))]
            if spec.hot_fraction > 0.0 and rng.random() < spec.hot_fraction:
                key = f"{spec.name}-hot{rng.randrange(spec.hot_keys)}"
            else:
                key = f"{spec.name}-{seq}"
            payload = {"text": text, "id": f"{spec.name}-{seq}",
                       "scenario": spec.name}
            if spec.emit_truth:
                payload["truth"] = 1 if scam else 0
            value = json.dumps(payload, sort_keys=True).encode()
            events.append(TrafficEvent(round(t, 6), value, key.encode(),
                                       "scam" if scam else "legit"))
            seq += 1
    return events


def compose(specs: Sequence[TrafficSpec],
            clock: ScenarioClock) -> List[TrafficEvent]:
    """Merge every spec's seeded events into one time-ordered timeline.
    Each spec draws from its own clock-derived stream, so adding or
    reordering specs never changes another spec's rows."""
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError(f"traffic spec names must be unique, got {names}")
    events: List[TrafficEvent] = []
    for spec in specs:
        events.extend(generate(spec, clock.derive_seed("traffic")))
    events.sort(key=lambda e: (e.t, e.key))
    return events


class TimelineAction(NamedTuple):
    """A scripted operator/fault action at a virtual time (hot swap, drain
    trigger, ...). ``fn`` runs on the scenario-feeder thread."""

    t: float
    name: str
    fn: Callable[[], None]


class TrafficFeeder:
    """The scenario-driver thread: walks the merged (events + actions)
    timeline in virtual-time order, producing rows to the input topic and
    firing actions at their times.

    One feeder per scenario run; ``start()`` spawns the single daemon
    thread ("scenario-feeder", registered in analysis/entrypoints.py),
    ``join()`` waits it out. Counters live under a small lock so
    ``stats()`` is safe from any thread; action exceptions are recorded in
    ``action_errors`` (a broken action fails the scenario's verdict, never
    the feeder). ``on_done`` runs last on the feeder thread — the game-day
    runner uses it to wait out the drain and stop the fleet."""

    def __init__(self, producer, topic: str,
                 events: Sequence[TrafficEvent], clock: ScenarioClock, *,
                 actions: Sequence[TimelineAction] = (),
                 on_done: Optional[Callable[[], None]] = None):
        self.producer = producer
        self.topic = topic
        self.events = list(events)
        self.actions = sorted(actions, key=lambda a: a.t)
        self.clock = clock
        self.on_done = on_done
        self._lock = threading.Lock()
        self._fed = 0
        self._actions_run: List[str] = []
        self.action_errors: List[tuple] = []    # (name, repr(exc))
        self.error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    # -- cross-thread surface -------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {"fed": self._fed, "planned": len(self.events),
                    "actions_run": list(self._actions_run),
                    "action_errors": list(self.action_errors)}

    @property
    def fed(self) -> int:
        with self._lock:
            return self._fed

    def alive(self) -> bool:
        """True while the feeder thread is still walking the timeline."""
        t = self._thread
        return t is not None and t.is_alive()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "TrafficFeeder":
        t = threading.Thread(target=self._run, name="scenario-feeder",
                             daemon=True)
        with self._lock:
            self._thread = t
        t.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError(
                    f"scenario feeder did not finish within {timeout}s "
                    f"({self.stats()})")

    def run_inline(self) -> None:
        """Drive the whole timeline on the CALLER's thread (replay CLI,
        tests that want strict sequencing)."""
        self._run()

    # -- feeder thread --------------------------------------------------

    def _run(self) -> None:
        try:
            self.clock.start()
            ai = 0
            actions = self.actions
            for ev in self.events:
                while ai < len(actions) and actions[ai].t <= ev.t:
                    self._fire(actions[ai])
                    ai += 1
                self.clock.advance_to(ev.t)
                self.producer.produce(self.topic, ev.value, key=ev.key)
                with self._lock:
                    self._fed += 1
            for act in actions[ai:]:
                self.clock.advance_to(act.t)
                self._fire(act)
            flush = getattr(self.producer, "flush", None)
            if flush is not None:
                flush()
            if self.on_done is not None:
                self.on_done()
        except BaseException as e:  # noqa: BLE001 — surfaced via .error
            self.error = e

    def _fire(self, action: TimelineAction) -> None:
        self.clock.advance_to(action.t)
        try:
            action.fn()
        except Exception as e:  # noqa: BLE001 — verdict-level failure
            with self._lock:
                self.action_errors.append((action.name, repr(e)))
            return
        with self._lock:
            self._actions_run.append(action.name)
