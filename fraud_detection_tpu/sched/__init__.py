"""Adaptive serving scheduler — the consume->score handoff, made load-aware.

The engine's original loop drains the consumer into a fixed-size micro-batch
and scores it, with no notion of offered load: a trickle pays full-batch
padding compute, and a flood has nowhere to go but queue growth. This
subsystem owns that handoff (docs/scheduling.md):

* :mod:`sketch` — bounded-memory streaming quantile sketch + EWMA; the
  per-row enqueue->produce latency accounting everything else reads.
* :mod:`batcher` — deadline-driven dynamic batching over a padding-bucket
  ladder, so partial batches ship early without fresh XLA compiles.
* :mod:`admission` — token-bucket rate limiting and queue-depth watermarks
  with EXPLICIT load shedding (structured records to the DLQ lane).
* :mod:`governor` — backpressure pacing from EWMAs of batch latency, so the
  engine degrades to bounded latency instead of unbounded memory.
* :mod:`scheduler` — the facade the engine drives
  (:class:`AdaptiveScheduler` + :class:`SchedulerConfig`).
"""

from fraud_detection_tpu.sched.admission import (AdmissionController,
                                                 TokenBucket)
from fraud_detection_tpu.sched.batcher import (DynamicBatcher,
                                               cost_aware_ladder,
                                               default_ladder,
                                               ladder_candidates,
                                               measure_rung_costs,
                                               prewarm_ladder)
from fraud_detection_tpu.sched.governor import BackpressureGovernor
from fraud_detection_tpu.sched.scheduler import (AdaptiveScheduler,
                                                 SchedulerConfig)
from fraud_detection_tpu.sched.sketch import Ewma, LatencySketch, SloTracker

__all__ = [
    "AdaptiveScheduler",
    "AdmissionController",
    "BackpressureGovernor",
    "DynamicBatcher",
    "Ewma",
    "LatencySketch",
    "SchedulerConfig",
    "SloTracker",
    "TokenBucket",
    "cost_aware_ladder",
    "default_ladder",
    "ladder_candidates",
    "measure_rung_costs",
    "prewarm_ladder",
]
