"""Admission control: token-bucket rate limiting + queue watermarks + shedding.

The engine's only overload response used to be implicit: the broker backlog
grows without bound until the consumer session dies. This module makes the
response EXPLICIT and accountable:

* a :class:`TokenBucket` meters admitted rows/sec against a configured rate;
* a queue-depth watermark (``max_queue``) bounds how much backlog the engine
  tolerates before shedding toward the watermark;
* an AIMD controller (policy ``adaptive``) sheds a growing fraction of each
  batch while the SLO tracker reports p99 over target, and backs off when
  latency recovers.

Shedding NEVER silently drops: every shed row becomes a structured record on
the DLQ lane, delivered and committed with the batch it was polled into —
the same flush/commit accounting as classified output, so a commit can never
advance past a lost shed record, and key-set accounting stays exact
(tests/test_sched.py). Rows are only ever shed at admission time, before
their batch dispatches; rows already in flight are never shed.

With policy ``none`` nothing is shed — the token bucket then degrades to a
pacing signal (``pending_pause``) the governor turns into poll backpressure.
"""

from __future__ import annotations

import math
import time
from typing import List, Optional, Tuple

SHED_POLICIES = ("none", "reject", "adaptive")

# Shed-record reasons (DLQ ``reason`` field + health counters).
SHED_QUEUE = "shed_queue_full"
SHED_RATE = "shed_rate_limit"
SHED_SLO = "shed_slo"
SHED_DEADLINE = "shed_deadline"

# With a latency target configured, rows already older than this fraction of
# the target at admission are shed (CoDel's insight: a row that has burned
# most of its deadline queueing will breach the SLO anyway — serving it
# spends capacity that fresh rows could still convert into on-target
# responses). Kept rows are young by construction, which is what actually
# bounds produced-row p99 under sustained overload.
SHED_AGE_FRACTION = 0.5


class TokenBucket:
    """Rows/sec token bucket with a burst ceiling.

    ``grant(n)`` returns how many of n rows fit the current budget (shedding
    policies divert the remainder). ``drain(n)`` admits all n unconditionally
    and returns the pacing debt in seconds — the no-shed policy's
    backpressure signal: polls slow down instead of rows dying."""

    def __init__(self, rate: float, burst: Optional[float] = None, *,
                 clock=time.monotonic):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(self.rate, 1.0)
        if self.burst <= 0:
            raise ValueError(f"burst must be > 0, got {self.burst}")
        self._clock = clock
        self._tokens = self.burst
        self._at = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._at) * self.rate)
        self._at = now

    def grant(self, n: int) -> int:
        self._refill()
        take = min(n, int(self._tokens))
        self._tokens -= take
        return take

    def drain(self, n: int) -> float:
        """Admit n rows, going into debt if needed; returns seconds of pacing
        required to repay the debt (0 when the budget covered the batch)."""
        self._refill()
        self._tokens -= n
        return max(0.0, -self._tokens) / self.rate

    @property
    def available(self) -> float:
        self._refill()
        return self._tokens


class AdmissionController:
    """Decides, per freshly polled batch, which rows score and which shed.

    Single-driver by contract (the scheduler's ExclusiveRegion enforces it);
    ``counters`` is read racily by health snapshots, which is fine for
    monotonic ints. Shedding always takes the NEWEST rows (the tail of the
    polled batch): the oldest rows have waited longest and are closest to
    their deadline, so shedding them would waste the queue time already
    spent — classic tail-dropping."""

    def __init__(self, policy: str = "none", *,
                 max_queue: Optional[int] = None,
                 bucket: Optional[TokenBucket] = None,
                 slo=None,
                 shed_step: float = 0.05,
                 shed_decay: float = 0.7,
                 wall=time.time):
        if policy not in SHED_POLICIES:
            raise ValueError(
                f"shed policy must be one of {SHED_POLICIES}, got {policy!r}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.policy = policy
        self.max_queue = max_queue
        self.bucket = bucket
        self.slo = slo
        self.shed_step = shed_step
        self.shed_decay = shed_decay
        self._wall = wall   # timestamps are broker wall-clock; ages must match
        # Age ceiling for kept rows under the adaptive policy (see
        # SHED_AGE_FRACTION); None = no age-based shedding.
        self.max_age_sec = (
            slo.target_p99_ms / 1e3 * SHED_AGE_FRACTION
            if (policy == "adaptive" and slo is not None
                and slo.target_p99_ms is not None) else None)
        # AIMD shed fraction for the adaptive policy: additive-ish increase
        # while p99 is over target, multiplicative decrease when it recovers.
        self.shed_fraction = 0.0
        self.counters = {SHED_QUEUE: 0, SHED_RATE: 0, SHED_SLO: 0,
                         SHED_DEADLINE: 0}
        self._pending_pause = 0.0
        self.last_backlog: Optional[int] = None

    @property
    def sheds(self) -> bool:
        return self.policy != "none"

    def pending_pause(self) -> float:
        """Seconds of poll pacing owed (policy ``none`` + token debt);
        cleared on read — the governor applies it exactly once."""
        pause, self._pending_pause = self._pending_pause, 0.0
        return pause

    def _update_shed_fraction(self) -> None:
        over = self.slo.over_target() if self.slo is not None else None
        if over is None:
            return
        if over:
            self.shed_fraction = min(
                1.0, self.shed_fraction * 1.5 + self.shed_step)
        else:
            f = self.shed_fraction * self.shed_decay
            self.shed_fraction = f if f > 1e-3 else 0.0

    def admit(self, msgs: List, backlog: Optional[int],
              trace=None) -> Tuple[List, List[Tuple[object, str]]]:
        """Split a polled batch into (kept, [(msg, shed_reason)]).

        ``backlog`` is the rows still queued BEHIND this batch at the broker
        (None when the transport can't report it — watermark shedding is
        then inert and only rate/SLO shedding applies). ``trace`` is the
        batch's obs.trace.BatchTrace when tracing is on: every shed row
        records a correlation-id'd event AT the shed site, so its span
        chain names the exact admission rule that diverted it."""
        self.last_backlog = backlog
        if not msgs:
            return msgs, []
        if self.policy == "none":
            if self.bucket is not None:
                self._pending_pause = self.bucket.drain(len(msgs))
            return msgs, []

        keep = msgs
        shed: List[Tuple[object, str]] = []

        def cut(n_keep: int, reason: str) -> None:
            nonlocal keep
            if n_keep < len(keep):
                shed.extend((m, reason) for m in keep[n_keep:])
                if trace is not None:
                    for m in keep[n_keep:]:
                        trace.shed(m, reason)
                self.counters[reason] += len(keep) - n_keep
                keep = keep[:n_keep]

        # Deadline shedding (adaptive policy with a target): rows that have
        # already burned SHED_AGE_FRACTION of the latency target queueing
        # cannot be served on-target — shed them, regardless of position,
        # so every KEPT row is young enough to finish inside the SLO. Rows
        # without a broker timestamp (0.0) are exempt (age unknowable).
        if self.max_age_sec is not None:
            cutoff = self._wall() - self.max_age_sec
            stale = [m for m in keep if 0.0 < m.timestamp < cutoff]
            if stale:
                shed.extend((m, SHED_DEADLINE) for m in stale)
                if trace is not None:
                    for m in stale:
                        trace.shed(m, SHED_DEADLINE)
                self.counters[SHED_DEADLINE] += len(stale)
                keep = [m for m in keep
                        if not 0.0 < m.timestamp < cutoff]

        # Queue watermark: over the high-water mark, shed proportionally to
        # the excess — a controller that drives backlog toward max_queue
        # while keeping some useful work flowing (shedding everything would
        # turn overload into an outage; shedding nothing lets the queue,
        # and therefore every row's latency, grow without bound).
        if (self.max_queue is not None and backlog is not None
                and backlog > self.max_queue):
            frac = (backlog - self.max_queue) / backlog
            cut(len(keep) - int(math.ceil(frac * len(keep))), SHED_QUEUE)

        if self.bucket is not None and keep:
            cut(self.bucket.grant(len(keep)), SHED_RATE)

        if self.policy == "adaptive" and keep:
            self._update_shed_fraction()
            if self.shed_fraction > 0.0:
                cut(len(keep) - int(math.ceil(
                    self.shed_fraction * len(keep))), SHED_SLO)

        return keep, shed

    def snapshot(self) -> dict:
        return {
            "policy": self.policy,
            "max_queue": self.max_queue,
            "rate_limit": self.bucket.rate if self.bucket is not None else None,
            "tokens_available": (round(self.bucket.available, 1)
                                 if self.bucket is not None else None),
            "shed_fraction": round(self.shed_fraction, 4),
            "shed": dict(self.counters),
            "backlog": self.last_backlog,
        }
