"""Deadline-driven dynamic batching over a padding-bucket ladder.

Two problems with the engine's fixed ``poll_batch(batch_size, max_wait)``:

* **Latency floor at low traffic.** A 3-row trickle either waits out
  ``max_wait`` hoping for more rows or ships immediately and pays the full
  ``batch_size`` padded device program either way (the pipeline pads every
  chunk to one compiled shape).
* **No accumulation window at medium traffic.** Rows arriving 1ms apart ship
  as many tiny batches instead of one efficient one, because the poll drains
  whatever is buffered and dispatches.

:class:`DynamicBatcher` forms batches by size OR deadline: after the first
row arrives, it keeps polling until the batch fills or ``deadline_ms``
elapses, then ships whatever it has. The partial batch then pads not to
``batch_size`` but to the smallest rung of a pre-warmed **bucket ladder**
(:func:`default_ladder`, e.g. 64/256/1024) — XLA's static-shape world means
every new shape is a fresh compile, so the ladder is the fixed menu of
shapes, each compiled once at startup (:func:`prewarm_ladder`), and the hot
path only ever snaps to one of them.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence

_MIN_BUCKET = 16

_PREWARM_TEXTS = [
    "urgent your account has been suspended verify your social security "
    "number immediately to avoid arrest and pay the processing fee now",
    "good morning thank you for calling the clinic i would like to confirm "
    "my appointment for tomorrow afternoon please bring your insurance card",
]


def default_ladder(batch_size: int, factor: int = 4,
                   levels: int = 3) -> tuple:
    """The padding-bucket ladder for a given max batch size: ``levels``
    geometric rungs ending at ``batch_size`` (1024 -> (64, 256, 1024)),
    floored at a minimum rung so tiny configs don't explode into one-row
    shapes. Ascending, deduplicated, always containing ``batch_size``."""
    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    if factor < 2:
        raise ValueError(f"factor must be >= 2, got {factor}")
    rungs = {max(_MIN_BUCKET, batch_size // factor ** i)
             for i in range(levels)}
    rungs.add(batch_size)
    return tuple(sorted(b for b in rungs if b <= batch_size))


def ladder_candidates(batch_size: int) -> tuple:
    """Probe rungs for cost measurement: geometric doublings from
    ``batch_size/16`` (floored at the minimum rung) up to ``batch_size`` —
    1024 -> (64, 128, 256, 512, 1024). A superset of :func:`default_ladder`
    so the measured cost curve can only refine the fixed geometry, never
    miss it."""
    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    rungs = {batch_size}
    b = max(_MIN_BUCKET, batch_size // 16)
    while b < batch_size:
        rungs.add(b)
        b *= 2
    return tuple(sorted(rungs))


def measure_rung_costs(pipeline, rungs: Sequence[int],
                       texts: Optional[Sequence[str]] = None,
                       repeats: int = 3) -> dict:
    """Per-rung steady device cost in seconds/batch, compile EXCLUDED.

    For each rung the pipeline's ladder pads an exactly-rung-sized batch to
    itself; the first run per rung carries the XLA compile (plus warm) and
    is never timed, then the median of ``repeats`` steady runs is recorded —
    a contention spike during one repeat shifts a sample, not the median.
    Times the raw-JSON path when the featurizer supports it (the engine's
    actual hot path), falling back to ``predict``. Leaves ``pad_ladder``
    set to ``rungs``; callers re-apply their selected ladder afterwards
    (every selected rung came from this probe set, so nothing compiles on
    the hot path later)."""
    pool = list(texts or _PREWARM_TEXTS)
    rungs = tuple(sorted({int(b) for b in rungs}))
    pipeline.pad_ladder = rungs
    costs = {}
    for b in rungs:
        rows = [pool[i % len(pool)] for i in range(b)]
        values = [json.dumps({"text": t}).encode() for t in rows]
        pipeline.predict(rows)                 # compile + warm (untimed)
        fast = pipeline.predict_json_async(values)
        if fast is not None:
            fast[0].resolve()
        samples = []
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            fast = pipeline.predict_json_async(values)
            if fast is not None:
                fast[0].resolve()
            else:
                pipeline.predict(rows)
            samples.append(time.perf_counter() - t0)
        samples.sort()
        costs[b] = samples[len(samples) // 2]
    return costs


def cost_aware_ladder(costs: dict, batch_size: int,
                      min_ratio: float = 1.25) -> tuple:
    """Derive ladder geometry from a measured cost curve (ROADMAP
    "Cost-aware bucket ladder") instead of the fixed /16 /4 /1 menu.

    Walk DOWN from the top rung and keep a smaller rung only when it is at
    least ``min_ratio`` cheaper than the smallest rung kept so far — in a
    flat region of the curve (fixed dispatch overhead dominating) padding a
    partial batch up to the next rung costs ~nothing, so the extra compiled
    shape buys nothing; where cost grows ~linearly every probe survives.
    The top rung (``batch_size``, else the largest measured) is always
    kept. The result is a subset of ``costs``' keys, so a caller that
    measured the candidates has already compiled every selected shape."""
    if min_ratio <= 1.0:
        raise ValueError(f"min_ratio must be > 1, got {min_ratio}")
    if not costs:
        raise ValueError("no measured rung costs")
    top = batch_size if batch_size in costs else max(costs)
    keep = [top]
    for b in sorted((x for x in costs if x < top), reverse=True):
        if costs[b] * min_ratio <= costs[keep[-1]]:
            keep.append(b)
    return tuple(sorted(keep))


def bucket_for(n: int, ladder: Sequence[int]) -> int:
    """Smallest rung >= n (the padding target for an n-row partial batch);
    the top rung for anything larger."""
    for b in ladder:
        if n <= b:
            return b
    return ladder[-1]


def prewarm_ladder(pipeline, buckets: Sequence[int],
                   texts: Optional[Sequence[str]] = None) -> int:
    """Compile every ladder shape off the hot path: configure the pipeline's
    ladder, then run one representative batch of EXACTLY each rung's row
    count through both scoring paths (plain predict + raw-JSON when the
    native featurizer supports it). Returns the number of rungs warmed.

    Must run with the ladder already applied — a 256-row dummy batch pads to
    the 256 rung, not to ``batch_size``, so warming each rung requires a
    batch of that exact size (the pre-ladder prewarm's single capped dummy
    batch no longer covers the shapes the hot path will use)."""
    pool = list(texts or _PREWARM_TEXTS)
    pipeline.pad_ladder = tuple(sorted(set(buckets)))
    warmed = 0
    for b in pipeline.pad_ladder:
        rows = [pool[i % len(pool)] for i in range(b)]
        pipeline.predict(rows)
        fast = pipeline.predict_json_async(
            [json.dumps({"text": t}).encode() for t in rows])
        if fast is not None:
            fast[0].resolve()
        warmed += 1
    return warmed


class DispatchLane:
    """Double-buffered async dispatch: ONE background thread runs the
    engine's featurize + upload + device-launch leg (``launch_fn``) for
    batch N+1 while the driver thread resolves / delivers batch N. With a
    device-featurizing pipeline (models/pipeline.py ``featurize_device``)
    the lane's leg is just decode + byte-pack + ONE raw-byte upload —
    tokenize/hash/count ride the device program, so the boundary this lane
    moves off the driver is down to a memcpy.

    The consume->score handoff today serializes the finish leg (device
    wait, frame assembly, produce, flush, commit) against the NEXT batch's
    host featurize on one thread; the lane moves featurize+launch off the
    driver so the device never waits on host featurize and the host never
    blocks on resolution except at delivery time (docs/serving.md
    "device-resident hot path"). ``depth`` bounds featurized-but-undelivered
    batches — 2 is classic double buffering: one staging buffer uploading/
    scoring while the alternate one fills.

    Contracts:

    * **Strict FIFO.** A single worker drains submissions in order and
      ``next()`` returns results in the same order, so the engine's offset
      commits stay ordered exactly as in synchronous mode.
    * **Failure transparency.** An exception inside ``launch_fn`` is
      re-raised from ``next()`` at the failed batch's FIFO position; the
      driver's abort path then discards newer batches uncommitted
      (at-least-once replay), exactly like a synchronous dispatch raise.
    * **Threading.** ``submit``/``next``/``stop``/``pending`` are
      driver-only (the engine's drive region guards the driver);
      ``stats()`` is safe from any thread. Queue and counters live under
      one condition variable.
    """

    def __init__(self, launch_fn: Callable, depth: int = 2, *,
                 name: str = "dispatch-lane"):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._launch_fn = launch_fn
        self.depth = depth
        self._cv = threading.Condition()
        self._in: deque = deque()      # submitted, not yet launched
        self._out: deque = deque()     # (inflight, exc) in submission order
        self._stopped = False
        self.submitted = 0
        self.launched = 0
        self.delivered = 0             # popped by next()
        self.waits = 0                 # next() calls that had to block
        self.max_inflight = 0          # peak submitted-minus-delivered
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    # driver surface
    # ------------------------------------------------------------------

    @property
    def pending(self) -> int:
        """Batches submitted but not yet returned by ``next()``."""
        with self._cv:
            return self.submitted - self.delivered

    def submit(self, item) -> None:
        with self._cv:
            if self._stopped:
                raise RuntimeError("dispatch lane is stopped")
            self._in.append(item)
            self.submitted += 1
            self.max_inflight = max(self.max_inflight,
                                    self.submitted - self.delivered)
            self._cv.notify_all()

    def next(self, timeout: Optional[float] = None):
        """Oldest launched batch (FIFO), blocking until the worker finishes
        it. Raises the worker's exception at that batch's position."""
        with self._cv:
            if not self._out:
                self.waits += 1
                if not self._cv.wait_for(lambda: bool(self._out),
                                         timeout=timeout):
                    raise TimeoutError(
                        f"dispatch lane produced nothing in {timeout}s "
                        f"(pending={self.submitted - self.delivered})")
            inflight, exc = self._out.popleft()
            self.delivered += 1
            if exc is not None:
                raise exc
            return inflight

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the worker and DISCARD anything not yet returned — the
        engine only calls this after draining what it intends to deliver;
        discarded batches were never committed, so a restart replays them
        (at-least-once, same as an abort in synchronous mode)."""
        with self._cv:
            self._stopped = True
            self._in.clear()
            self._cv.notify_all()
        self._thread.join(timeout)

    def stats(self) -> dict:
        with self._cv:
            return {
                "depth": self.depth,
                "submitted": self.submitted,
                "launched": self.launched,
                "max_inflight": self.max_inflight,
                "driver_waits": self.waits,
            }

    # ------------------------------------------------------------------
    # worker
    # ------------------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._in and not self._stopped:
                    self._cv.wait()
                if self._stopped:
                    return
                item = self._in.popleft()
            inflight, exc = None, None
            try:
                inflight = self._launch_fn(item)
            except BaseException as e:  # noqa: BLE001 — re-raised in next()
                exc = e
            with self._cv:
                self._out.append((inflight, exc))
                self.launched += 1
                self._cv.notify_all()


class DynamicBatcher:
    """Form micro-batches by size or deadline from a Consumer.

    ``collect`` is the engine's poll replacement: wait up to ``first_wait``
    for the first row (the engine's existing idle cadence), then accumulate
    until the batch fills or ``deadline_ms`` has elapsed since the first
    poll returned rows. ``deadline_ms=None`` degrades to a single plain
    poll — the scheduler without a deadline batches exactly like the bare
    engine. Single-driver by contract (the owning scheduler's region
    enforces it)."""

    def __init__(self, deadline_ms: Optional[float] = None, *,
                 poll_slice: float = 0.005, clock=time.monotonic):
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        if poll_slice <= 0:
            raise ValueError(f"poll_slice must be > 0, got {poll_slice}")
        self.deadline_ms = deadline_ms
        self.poll_slice = poll_slice
        self._clock = clock

    def collect(self, consumer, budget: int, first_wait: float) -> List:
        msgs = consumer.poll_batch(budget, first_wait)
        if not msgs or self.deadline_ms is None or len(msgs) >= budget:
            return msgs
        # The deadline anchors at the first non-empty poll's return — the
        # closest host-side proxy for the first row's arrival. Remaining
        # capacity is topped up in short poll slices so a burst landing
        # mid-window ships as one batch instead of many.
        deadline = self._clock() + self.deadline_ms / 1e3
        while len(msgs) < budget:
            remaining = deadline - self._clock()
            if remaining <= 0:
                break
            more = consumer.poll_batch(budget - len(msgs),
                                       min(remaining, self.poll_slice))
            if more:
                msgs.extend(more)
        return msgs
