"""Backpressure governor: pace consumer polls from observed latency EWMAs.

Two degradation modes the bare engine had no answer for:

* **Batch wall blowup.** A slow device (contended TPU, tunnel latency spike)
  makes each full-size batch take seconds; every row polled into such a
  batch inherits that wall as queue time, and — on a real broker — a poll
  interval that outgrows ``max.poll.interval.ms`` gets the consumer evicted,
  turning slowness into an outage. The governor caps the poll budget so the
  PREDICTED batch wall (EWMA per-row seconds x budget) stays under a bound:
  smaller batches, steadier poll cadence, bounded per-batch latency.
* **Rate-limit pacing.** With ``shed_policy=none`` a token bucket cannot
  shed; the admission controller instead reports pacing debt, and the
  governor converts it into a pre-poll pause — backpressure by slowing
  intake, not by dropping rows.

The EWMAs observe DELIVERED batches (rows, wall seconds); the budget cap is
recomputed per poll from the current estimate, so the governor tracks load
shifts at EWMA speed and relaxes back to full batches when pressure clears.
"""

from __future__ import annotations

from typing import Optional, Tuple

from fraud_detection_tpu.sched.sketch import Ewma


class BackpressureGovernor:
    """Advises (poll budget, pause seconds) before each poll.

    ``max_batch_sec`` bounds the predicted batch wall; None disables the
    cap. ``min_budget`` floors the cap so pathological EWMA readings can't
    starve the engine down to one-row batches (the smallest ladder rung is
    the natural floor). Single-driver by contract, like the batcher."""

    def __init__(self, max_batch_sec: Optional[float] = None, *,
                 min_budget: int = 16, alpha: float = 0.2,
                 max_pause_sec: float = 1.0):
        if max_batch_sec is not None and max_batch_sec <= 0:
            raise ValueError(
                f"max_batch_sec must be > 0, got {max_batch_sec}")
        if min_budget < 1:
            raise ValueError(f"min_budget must be >= 1, got {min_budget}")
        self.max_batch_sec = max_batch_sec
        self.min_budget = min_budget
        self.max_pause_sec = max_pause_sec
        self.ewma_batch_sec = Ewma(alpha)
        self.ewma_row_sec = Ewma(alpha)
        self.budget_caps = 0   # polls whose budget the governor reduced
        self.paused_sec = 0.0  # cumulative pacing applied

    def observe(self, n_rows: int, batch_sec: float) -> None:
        """Feed one delivered batch's (row count, processing wall)."""
        if n_rows <= 0:
            return
        self.ewma_batch_sec.observe(batch_sec)
        self.ewma_row_sec.observe(batch_sec / n_rows)

    def advise(self, budget: int, pacing_debt: float = 0.0
               ) -> Tuple[int, float]:
        """(possibly reduced budget, pause seconds) for the next poll."""
        row_sec = self.ewma_row_sec.value
        if (self.max_batch_sec is not None and row_sec is not None
                and row_sec > 0):
            cap = max(self.min_budget, int(self.max_batch_sec / row_sec))
            if cap < budget:
                budget = cap
                self.budget_caps += 1
        pause = min(max(0.0, pacing_debt), self.max_pause_sec)
        if pause > 0:
            self.paused_sec += pause
        return budget, pause

    def snapshot(self) -> dict:
        row = self.ewma_row_sec.value
        batch = self.ewma_batch_sec.value
        return {
            "max_batch_sec": self.max_batch_sec,
            "ewma_batch_ms": None if batch is None else round(batch * 1e3, 3),
            "ewma_row_us": None if row is None else round(row * 1e6, 2),
            "budget_caps": self.budget_caps,
            "paused_sec": round(self.paused_sec, 3),
        }
