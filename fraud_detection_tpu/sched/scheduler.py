"""The scheduler facade the streaming engine drives.

:class:`SchedulerConfig` is the validated knob set (the serve CLI's
``--batch-deadline-ms/--max-queue/--shed-policy/--target-p99-ms/--max-rate``
map straight onto it); :class:`AdaptiveScheduler` wires the four parts —
dynamic batcher, admission controller, backpressure governor, windowed SLO
tracker — behind the three calls the engine makes per batch:

* ``collect(consumer, budget, first_wait)`` — governor-paced, deadline-driven
  poll (replaces the bare ``poll_batch``);
* ``admit(msgs, backlog)`` — split the fresh batch into kept rows and
  explicit shed records (empty under policy ``none``);
* ``observe_batch(n_rows, batch_sec, row_latencies)`` — feed the EWMAs and
  the SLO window after delivery.

One scheduler instance serves ONE engine: collect/admit share mutable batch
state and are guarded by an :class:`ExclusiveRegion` (the same single-driver
contract the engine itself checks), while ``snapshot()`` is safe from any
thread (health pollers read it live).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from fraud_detection_tpu.sched.admission import (SHED_POLICIES,
                                                 AdmissionController,
                                                 TokenBucket)
from fraud_detection_tpu.sched.batcher import (DynamicBatcher, bucket_for,
                                               cost_aware_ladder,
                                               default_ladder,
                                               ladder_candidates,
                                               measure_rung_costs,
                                               prewarm_ladder)
from fraud_detection_tpu.sched.governor import BackpressureGovernor
from fraud_detection_tpu.sched.sketch import SloTracker
from fraud_detection_tpu.utils.racecheck import ExclusiveRegion


@dataclass(frozen=True)
class SchedulerConfig:
    """Validated scheduler knobs (docs/scheduling.md has the tuning guide).

    All-defaults means "scheduler attached but maximally transparent":
    no deadline (single poll), no shedding, no rate limit, a generous
    batch-wall bound. Anything the operator doesn't set stays out of the
    control loop."""

    batch_deadline_ms: Optional[float] = None
    max_queue: Optional[int] = None
    shed_policy: str = "none"
    target_p99_ms: Optional[float] = None
    max_rate: Optional[float] = None      # admitted rows/sec; None = off
    burst: Optional[float] = None         # token burst; None = 1s of rate
    window_sec: float = 10.0              # SLO tracker rotation window
    max_batch_sec: Optional[float] = None  # None = derived (see resolve)
    buckets: Optional[Tuple[int, ...]] = None  # None = measured (cost_aware)
                                               # else default_ladder
    # Cost-aware ladder (docs/scheduling.md): prewarm() times every
    # candidate rung (compile excluded, median of steady repeats) and
    # derives the rung set from the measured cost curve; explicit
    # ``buckets`` pin the geometry but the rungs still get measured for
    # the health()/bench cost table. cost_ratio is the minimum cost gap
    # that justifies keeping a smaller rung.
    cost_aware: bool = True
    cost_ratio: float = 1.25

    def __post_init__(self):
        if self.shed_policy not in SHED_POLICIES:
            raise ValueError(
                f"shed_policy must be one of {SHED_POLICIES}, "
                f"got {self.shed_policy!r}")
        if self.batch_deadline_ms is not None and self.batch_deadline_ms <= 0:
            raise ValueError(
                f"batch_deadline_ms must be > 0, got {self.batch_deadline_ms}")
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if self.target_p99_ms is not None and self.target_p99_ms <= 0:
            raise ValueError(
                f"target_p99_ms must be > 0, got {self.target_p99_ms}")
        if self.max_rate is not None and self.max_rate <= 0:
            raise ValueError(f"max_rate must be > 0, got {self.max_rate}")
        if self.window_sec <= 0:
            raise ValueError(f"window_sec must be > 0, got {self.window_sec}")
        if self.shed_policy == "adaptive" and self.target_p99_ms is None:
            raise ValueError(
                "shed_policy='adaptive' sheds on SLO pressure and needs "
                "target_p99_ms")
        if self.shed_policy == "reject" and (self.max_queue is None
                                             and self.max_rate is None):
            raise ValueError(
                "shed_policy='reject' needs a limit to enforce: set "
                "max_queue and/or max_rate")
        if self.cost_ratio <= 1.0:
            raise ValueError(f"cost_ratio must be > 1, got {self.cost_ratio}")

    def resolved_max_batch_sec(self) -> float:
        """The governor's batch-wall bound. Explicit value wins; with a
        latency target, half the target (queue wait needs the other half);
        otherwise a 2s backstop that exists to keep poll cadence inside any
        sane broker session timeout."""
        if self.max_batch_sec is not None:
            return self.max_batch_sec
        if self.target_p99_ms is not None:
            return self.target_p99_ms / 2e3
        return 2.0


class AdaptiveScheduler:
    """One engine's consume->score scheduler (see module docstring)."""

    def __init__(self, config: SchedulerConfig, batch_size: int, *,
                 clock=time.monotonic, sleep=time.sleep):
        self.config = config
        self.batch_size = batch_size
        self.buckets: Tuple[int, ...] = tuple(
            config.buckets if config.buckets
            else default_ladder(batch_size))
        # Measured per-rung device cost (seconds/batch, compile excluded) —
        # populated by prewarm(); the geometry source under cost_aware and
        # the health()/bench evidence either way.
        self.ladder_costs: Optional[dict] = None
        self.slo = SloTracker(target_p99_ms=config.target_p99_ms,
                              window_sec=config.window_sec, clock=clock)
        self.batcher = DynamicBatcher(config.batch_deadline_ms, clock=clock)
        bucket = (TokenBucket(config.max_rate, config.burst, clock=clock)
                  if config.max_rate is not None else None)
        self.admission = AdmissionController(
            config.shed_policy, max_queue=config.max_queue,
            bucket=bucket, slo=self.slo)
        self.governor = BackpressureGovernor(
            config.resolved_max_batch_sec(),
            min_budget=self.buckets[0])
        self._sleep = sleep
        # Fleet-coordinated shedding (fleet/coordinator.py, docs/fleet.md):
        # an optional zero-arg callable returning the fleet's aggregated
        # backlog-per-worker (None when the fleet view is stale/absent).
        # When it reports MORE queued work than this worker's own
        # partitions show, admission sheds against the global watermark —
        # a drowning fleet sheds everywhere at once instead of each worker
        # guessing from its own slice. None (the default) keeps the purely
        # local signal.
        self.fleet_backlog: Optional[callable] = None
        # collect/admit mutate shared control state (token bucket, EWMAs,
        # AIMD fraction) and are single-driver by the same contract as the
        # engine loop that calls them; snapshot() deliberately does NOT
        # enter the region (health pollers read from other threads).
        self._region = ExclusiveRegion("AdaptiveScheduler.drive")

    # ------------------------------------------------------------------
    # engine-facing surface (engine thread only)
    # ------------------------------------------------------------------

    @property
    def sheds(self) -> bool:
        """True when the policy can divert rows (the engine then requires a
        DLQ topic for the shed records to land on)."""
        return self.admission.sheds

    def collect(self, consumer, budget: int, first_wait: float) -> List:
        """Governor-paced, deadline-driven poll of up to ``budget`` rows."""
        with self._region:
            budget, pause = self.governor.advise(
                budget, self.admission.pending_pause())
            if pause > 0:
                self._sleep(pause)
            return self.batcher.collect(consumer, budget, first_wait)

    def backlog_of(self, consumer) -> Optional[int]:
        """The queue-depth signal admission sheds against: rows queued
        behind this worker's poll position (InProcessConsumer.backlog; None
        when the transport can't report it), raised to the fleet's
        backlog-per-worker watermark when a ``fleet_backlog`` source is
        wired and reports more (the global number keeps each worker's
        ``max_queue`` threshold meaningful while coordinating WHEN the
        fleet sheds)."""
        backlog = getattr(consumer, "backlog", None)
        local: Optional[int] = None
        if backlog is not None:
            try:
                local = backlog()
            except Exception:  # noqa: BLE001 — lag reporting must never kill serving
                local = None
        fleet = self.fleet_backlog
        if fleet is not None:
            try:
                g = fleet()
            except Exception:  # noqa: BLE001 — same contract as the local probe
                g = None
            if g is not None:
                return max(int(g), local if local is not None else 0)
        return local

    def admit(self, msgs: List, backlog: Optional[int],
              trace=None) -> Tuple[List, List[Tuple[object, str]]]:
        with self._region:
            return self.admission.admit(msgs, backlog, trace=trace)

    def observe_batch(self, n_rows: int, batch_sec: float,
                      row_latencies: Optional[Sequence[float]] = None) -> None:
        with self._region:
            self.governor.observe(n_rows, batch_sec)
            if row_latencies is not None and len(row_latencies):
                self.slo.record(row_latencies)

    def bucket_for(self, n: int) -> int:
        return bucket_for(n, self.buckets)

    def prewarm(self, pipeline,
                texts: Optional[Sequence[str]] = None) -> int:
        """Measure rung costs, derive the ladder, apply it to the pipeline,
        and compile every selected rung off the hot path.

        Under ``cost_aware`` (the default, no explicit ``buckets``) the
        candidate rungs (sched/batcher.py ladder_candidates) are each timed
        at prewarm — compile excluded, median of steady repeats — and the
        rung geometry comes from the measured cost curve
        (``cost_aware_ladder``) instead of the fixed /16 /4 /1 menu.
        Explicit ``buckets`` pin the geometry; the rungs are still measured
        so health()/bench carry the cost table. HotSwapPipelines measure on
        the ACTIVE pipeline and cache the costs, so future swap candidates
        only compile the selected rungs — they never re-bench
        (registry/hotswap.py)."""
        # Prewarm mutates driver-owned control state (buckets, ladder_costs,
        # the governor's budget floor) that snapshot() reads from health-
        # poller threads — it is part of the single-driver contract and
        # enters the region like collect/admit/observe_batch do (flightcheck
        # FC102 caught the original unguarded writes; same-thread re-entry
        # is free, a concurrent driver is a RaceError).
        with self._region:
            cfg = self.config
            explicit = cfg.buckets is not None
            candidates = (self.buckets if explicit or not cfg.cost_aware
                          else ladder_candidates(self.batch_size))
            measure = getattr(pipeline, "measure_ladder", None)
            if measure is not None:     # HotSwapPipeline: measure + cache
                costs = measure(candidates, texts=texts)
            else:
                costs = measure_rung_costs(pipeline, candidates, texts=texts)
            self.ladder_costs = dict(costs)
            if not explicit and cfg.cost_aware:
                self.buckets = cost_aware_ladder(costs, self.batch_size,
                                                 cfg.cost_ratio)
                # The smallest rung is the governor's budget floor — keep
                # them aligned when measurement reshapes the ladder.
                self.governor.min_budget = self.buckets[0]
            configure = getattr(pipeline, "configure_ladder", None)
            if configure is not None:
                configure(self.buckets, prewarm=True, costs=costs)
                return len(self.buckets)
            # Every selected rung was compiled during measurement; this
            # applies the final ladder and re-warms it (no new compiles).
            prewarm_ladder(pipeline, self.buckets, texts)
            return len(self.buckets)

    # ------------------------------------------------------------------
    # observability (any thread)
    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``sched`` block of ``StreamingClassifier.health()``."""
        costs = self.ladder_costs
        return {
            "batch_deadline_ms": self.config.batch_deadline_ms,
            "buckets": list(self.buckets),
            # Measured per-rung device cost (ms/batch, compile excluded) —
            # None until prewarm() ran. Keys are strings for JSON pollers.
            "ladder_cost_ms": (None if costs is None else
                               {str(b): round(s * 1e3, 3)
                                for b, s in sorted(costs.items())}),
            "slo": self.slo.snapshot(),
            "admission": self.admission.snapshot(),
            "governor": self.governor.snapshot(),
        }
