"""Streaming latency accounting: quantile sketch, EWMA, windowed SLO tracker.

``StreamStats`` kept a bounded reservoir of PER-BATCH device latencies; under
load that undercounts what a caller actually experiences, because a row's
latency is dominated by the time it spends queued behind other batches. The
scheduler needs per-ROW enqueue->produce quantiles, online, at 50k rows/sec,
readable from other threads (health pollers) while the engine writes — which
rules out storing samples. :class:`LatencySketch` is the answer: an
HDR-histogram-style log-bucketed counter array with bounded memory, vectorized
batch inserts, exact counts, and mergeable across supervised incarnations.
Quantiles are exact up to the bucket's relative width (~7%), which is far
inside the run-to-run noise of any latency measurement this framework makes.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional, Sequence

import numpy as np

# Bucket geometry: [10us, ~1000s) at 7% relative width. One int64 per bucket
# keeps the whole sketch ~2KB — cheap enough for one per engine incarnation
# plus two per scheduler window.
_MIN_SEC = 1e-5
_GROWTH = 1.07
_N_BUCKETS = int(math.ceil(math.log(1e8) / math.log(_GROWTH)))  # ~273
# Upper edge of bucket i; quantile queries report the upper edge, so the
# estimate errs toward overstating latency (the conservative direction for
# an SLO check).
_EDGES = _MIN_SEC * _GROWTH ** np.arange(1, _N_BUCKETS + 1)


class LatencySketch:
    """Bounded-memory streaming quantile sketch over seconds-valued samples.

    Thread-safe: writers (the engine's per-batch ``add_many``) and readers
    (health pollers calling ``quantile``/``snapshot``) take one small lock
    per CALL, never per sample. Mergeable: supervised restarts aggregate
    incarnation sketches losslessly (counts add), unlike the reservoir,
    whose merge is a subsample.
    """

    __slots__ = ("_counts", "_lock", "count", "sum", "max")

    def __init__(self):
        self._counts = np.zeros(_N_BUCKETS, np.int64)
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def add(self, sec: float) -> None:
        self.add_many(np.asarray([sec], np.float64))

    def add_many(self, secs) -> None:
        """Insert a batch of samples (seconds). One vectorized pass + one
        lock acquisition regardless of batch size."""
        arr = np.asarray(secs, np.float64)
        if arr.size == 0:
            return
        arr = np.maximum(arr, 0.0)  # clock skew can produce tiny negatives
        idx = np.searchsorted(_EDGES, arr, side="left")
        idx = np.minimum(idx, _N_BUCKETS - 1)
        binned = np.bincount(idx, minlength=_N_BUCKETS).astype(np.int64)
        with self._lock:
            self._counts += binned
            self.count += int(arr.size)
            self.sum += float(arr.sum())
            self.max = max(self.max, float(arr.max()))

    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile (q in [0, 1]) in seconds, or None when empty.
        Reports the holding bucket's upper edge (conservative for SLOs)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            if self.count == 0:
                return None
            target = q * self.count
            cum = np.cumsum(self._counts)
            i = int(np.searchsorted(cum, target, side="left"))
        return float(_EDGES[min(i, _N_BUCKETS - 1)])

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def merge(self, other: "LatencySketch") -> None:
        """Lossless merge (bucket counts add). Lock order: always take
        self's lock first against a snapshot of other — merge callers
        (supervised stat aggregation) own ``other`` exclusively."""
        with other._lock:
            counts = other._counts.copy()
            count, total, mx = other.count, other.sum, other.max
        with self._lock:
            self._counts += counts
            self.count += count
            self.sum += total
            self.max = max(self.max, mx)

    def to_wire(self) -> dict:
        """JSON-safe sparse encoding of the full sketch (non-zero bucket
        indexes + counts + exact aggregates). ``from_wire`` round-trips it
        losslessly, which is what lets fleet workers publish sketches on
        the bus and the coordinator merge them into EXACTLY the sketch a
        single process would have built (obs/trace.py aggregation)."""
        with self._lock:
            idx = np.flatnonzero(self._counts)
            return {"v": 1,
                    "idx": idx.tolist(),
                    "counts": self._counts[idx].tolist(),
                    "count": self.count,
                    "sum": self.sum,
                    "max": self.max}

    @classmethod
    def from_wire(cls, wire) -> Optional["LatencySketch"]:
        """Rebuild a sketch from :meth:`to_wire` output; None on any
        malformed/foreign payload (bus docs cross process boundaries —
        telemetry ingest must never raise)."""
        try:
            if not isinstance(wire, dict) or wire.get("v") != 1:
                return None
            sk = cls()
            idx = np.asarray(wire["idx"], np.int64)
            counts = np.asarray(wire["counts"], np.int64)
            if idx.shape != counts.shape or (
                    idx.size and (idx.min() < 0 or idx.max() >= _N_BUCKETS)):
                return None
            sk._counts[idx] = counts
            sk.count = int(wire["count"])
            sk.sum = float(wire["sum"])
            sk.max = float(wire["max"])
            return sk
        except (KeyError, TypeError, ValueError):
            return None

    def snapshot(self) -> dict:
        """p50/p95/p99/max/mean in milliseconds + count, one consistent read."""
        with self._lock:
            if self.count == 0:
                return {"count": 0, "p50_ms": None, "p95_ms": None,
                        "p99_ms": None, "mean_ms": None, "max_ms": None}
            cum = np.cumsum(self._counts)
            count, total, mx = self.count, self.sum, self.max

        def q(frac: float) -> float:
            i = int(np.searchsorted(cum, frac * count, side="left"))
            return float(_EDGES[min(i, _N_BUCKETS - 1)])

        return {"count": count,
                "p50_ms": round(q(0.50) * 1e3, 3),
                "p95_ms": round(q(0.95) * 1e3, 3),
                "p99_ms": round(q(0.99) * 1e3, 3),
                "mean_ms": round(total / count * 1e3, 3),
                "max_ms": round(mx * 1e3, 3)}


class Ewma:
    """Exponentially weighted moving average; None until the first observe."""

    __slots__ = ("alpha", "value")

    def __init__(self, alpha: float = 0.2):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self.value: Optional[float] = None

    def observe(self, x: float) -> float:
        self.value = (x if self.value is None
                      else self.alpha * x + (1.0 - self.alpha) * self.value)
        return self.value


class SloTracker:
    """Windowed per-row latency quantiles feeding the governor and shedding.

    Two-sketch rotation: samples land in the CURRENT sketch; every
    ``window_sec`` it rotates to PREVIOUS and a fresh current starts.
    Queries merge both, so estimates cover the last 1-2 windows — recent
    enough for control decisions, smooth enough not to flap on one batch.
    A cumulative all-time sketch is the engine's ``StreamStats`` job, not
    this class's.
    """

    def __init__(self, target_p99_ms: Optional[float] = None,
                 window_sec: float = 10.0, clock=None):
        if window_sec <= 0:
            raise ValueError(f"window_sec must be > 0, got {window_sec}")
        if target_p99_ms is not None and target_p99_ms <= 0:
            raise ValueError(
                f"target_p99_ms must be > 0, got {target_p99_ms}")
        self.target_p99_ms = target_p99_ms
        self.window_sec = window_sec
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._current = LatencySketch()
        self._previous = LatencySketch()
        self._rotated_at = self._clock()

    def _maybe_rotate_locked(self, now: float) -> None:
        if now - self._rotated_at >= self.window_sec:
            self._previous = self._current
            self._current = LatencySketch()
            self._rotated_at = now

    def record(self, secs: Sequence[float]) -> None:
        now = self._clock()
        with self._lock:
            self._maybe_rotate_locked(now)
            current = self._current
        current.add_many(secs)

    def _merged(self) -> LatencySketch:
        with self._lock:
            self._maybe_rotate_locked(self._clock())
            current, previous = self._current, self._previous
        merged = LatencySketch()
        merged.merge(previous)
        merged.merge(current)
        return merged

    def p99_ms(self) -> Optional[float]:
        q = self._merged().quantile(0.99)
        return None if q is None else q * 1e3

    def over_target(self) -> Optional[bool]:
        """True/False vs the configured target; None when no target or no
        samples yet (callers must treat None as 'no pressure signal')."""
        if self.target_p99_ms is None:
            return None
        p99 = self.p99_ms()
        return None if p99 is None else p99 > self.target_p99_ms

    def snapshot(self) -> dict:
        snap = self._merged().snapshot()
        snap["target_p99_ms"] = self.target_p99_ms
        snap["window_sec"] = self.window_sec
        return snap
