from fraud_detection_tpu.stream.annotations import AsyncAnnotationLane
from fraud_detection_tpu.stream.broker import CommitFailedError, InProcessBroker, Message
from fraud_detection_tpu.stream.engine import StreamingClassifier, StreamStats
from fraud_detection_tpu.stream.kafka import kafka_available

__all__ = ["AsyncAnnotationLane", "CommitFailedError", "InProcessBroker", "Message", "StreamingClassifier", "StreamStats",
           "kafka_available"]
