from fraud_detection_tpu.stream.annotations import AsyncAnnotationLane
from fraud_detection_tpu.stream.broker import (CommitFailedError, InProcessBroker,
                                               Message, TransientBrokerError)
from fraud_detection_tpu.stream.engine import StreamingClassifier, StreamStats
from fraud_detection_tpu.stream.faults import ChaosConsumer, ChaosProducer, FaultPlan
from fraud_detection_tpu.stream.kafka import kafka_available

__all__ = ["AsyncAnnotationLane", "ChaosConsumer", "ChaosProducer",
           "CommitFailedError", "FaultPlan", "InProcessBroker", "Message",
           "StreamingClassifier", "StreamStats", "TransientBrokerError",
           "kafka_available"]
