from fraud_detection_tpu.stream.broker import InProcessBroker, Message
from fraud_detection_tpu.stream.engine import StreamingClassifier, StreamStats
from fraud_detection_tpu.stream.kafka import kafka_available

__all__ = ["InProcessBroker", "Message", "StreamingClassifier", "StreamStats",
           "kafka_available"]
