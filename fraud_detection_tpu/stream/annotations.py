"""Asynchronous LLM annotation lane: classification never waits for decode.

The reference pays a BLOCKING LLM round-trip inside its per-message serve
loop (app_ui.py:195-248 — one DeepSeek HTTPS call per flagged dialogue, so
stream throughput collapses to the LLM's rate). The inline
``explain_batch_fn`` hook here already amortizes that to one on-pod device
program per micro-batch, but it still serializes CLASSIFICATION behind
DECODE: a multi-second 48-token batch generate caps the whole stream at the
annotator's ~dozen explanations/sec (measured: 5.2k msgs/s no-hook vs ~114
with the inline hook on one chip).

This lane decouples them. Flagged rows are copied into a bounded queue and
the classified frames go out IMMEDIATELY (no "analysis" field — which also
keeps the native raw-JSON frame path, disabled under inline hooks, in
play); a single worker thread drains the queue in micro-batches through the
same hook signature and produces annotation records to a side topic
(``<output_topic>-annotations``), keyed like their source messages so they
partition identically. When flagged rows arrive faster than the LLM can
decode — the steady state: 5% of 30k/s is ~1.5k flagged/s against ~12
explanations/s — the queue drops OLDEST first and counts it: annotating a
recent sample beats throttling classification 250x, and the drop counter
makes the sampling rate an explicit, recorded fact rather than a stall.

Consumers join annotations to classifications by message key (the
classified frame stream stays complete; annotations are best-effort
enrichment). Degraded mode matches the inline hook's: a raising backend is
logged and dropped, classification untouched.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, List

from fraud_detection_tpu.explain.prompts import label_name
from fraud_detection_tpu.utils import get_logger

log = get_logger("stream.annotations")


class AsyncAnnotationLane:
    """Bounded background annotator feeding a side topic.

    ``explain_batch_fn``: the SAME hook shape the inline path takes
    ((texts, labels, confs) -> [analysis | None]) — e.g.
    ``make_stream_explain_hook(OnPodBackend...)``. Rows whose analysis
    comes back None produce no record (the hook's own selection policy).

    ``producer``/``topic``: where annotation records go. Records are JSON:
    ``{"prediction", "label", "confidence", "analysis"}`` keyed by the
    source message's key. The producer must be the lane's OWN (a second
    client on the same transport), never shared with the engine: flush()
    is how both sides account delivery, and sharing would let either side
    consume the other's failures (StreamingClassifier enforces this).
    """

    def __init__(self, explain_batch_fn: Callable, producer, topic: str, *,
                 max_queue: int = 1024, max_batch: int = 64,
                 rowtrace=None,
                 clock: Callable[[], float] = time.perf_counter):
        if max_queue < 1 or max_batch < 1:
            raise ValueError(
                f"max_queue/max_batch must be >= 1, got {max_queue}/{max_batch}")
        self._clock = clock   # injectable: drain/close deadlines in tests
        # Optional obs.trace.RowTracer: items may carry a 5th element (the
        # row's correlation id), and the lane then records an "explain"
        # span per backend call plus an "annotate" event per row — ok=False
        # on backend errors AND breaker fast-fails, so a flagged row's
        # chain shows exactly where its explanation died. Flagged rows are
        # always-kept by the tracer, so these record directly to the ring.
        self._rowtrace = rowtrace
        self._fn = explain_batch_fn
        self._producer = producer
        self.topic = topic
        self.max_queue = max_queue
        self.max_batch = max_batch
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        # Structured drop records pending emission (built at the drop
        # site under _cv, produced by the WORKER so they ride the lane's
        # single-producer delivery accounting): (value_bytes, key, cid).
        self._drop_backlog: List[tuple] = []
        # Counters guarded by _cv's lock (submitted/dropped mutate under it);
        # annotated/errors are worker-thread-only writes, read-racy by design
        # (stats snapshots, not invariants).
        self.submitted = 0
        self.dropped = 0
        # Drop records DELIVERED to the side topic (worker-thread tally,
        # like ``annotated``): a drop-OLDEST eviction is not a bare
        # counter — it emits a structured record carrying the row's trace
        # cid, so under slotserve every flagged row is explained OR
        # accounted, join-able to ``chain(cid)``. ``dropped`` >
        # ``drop_records`` only for close()-residual discards (no worker
        # left to deliver them) or undelivered flushes — both logged.
        self.drop_records = 0
        self.annotated = 0
        self.backend_errors = 0
        # Records handed to the producer across the lane's lifetime: the
        # ``annotated`` credit is the running delivered total (produced -
        # flush()'s producer-queue depth), NOT a per-batch subtraction —
        # flush() counts the whole producer queue, so records a previous
        # failed flush left behind would otherwise be double-subtracted
        # (ADVICE round 5). Worker-thread-only, like ``annotated``.
        self.produced = 0
        self._idle = threading.Event()
        self._idle.set()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="annotation-lane")
        self._thread.start()

    def submit(self, items: List[tuple]) -> None:
        """Enqueue (key, text, label, confidence[, trace_cid]) rows;
        never blocks.

        Over capacity, the OLDEST queued rows are dropped and counted —
        under sustained overload the lane annotates a sliding recent
        sample — and each eviction leaves a STRUCTURED drop record
        (``{"dropped": true, "reason": "queue_overflow", "trace": cid}``
        keyed like the source row) for the worker to produce to the side
        topic: the sampling rate is a recorded, join-able fact per row,
        not a bare counter.
        """
        if not items:
            return
        with self._cv:
            if self._closed:
                return
            for it in items:
                if len(self._q) >= self.max_queue:
                    old = self._q.popleft()
                    self.dropped += 1
                    self._drop_backlog.append(
                        self._drop_record(old, "queue_overflow"))
                self._q.append(it)
            self.submitted += len(items)
            self._idle.clear()
            self._cv.notify()

    @staticmethod
    def _drop_record(item: tuple, reason: str) -> tuple:
        """Build one structured drop record from a queued item; returns
        (value_bytes, key, cid). Schema mirrors the annotation record
        (docs/robustness.md): same key, ``analysis`` null, ``dropped``
        true, ``trace`` = the row's correlation id when the engine traces
        — a DLQ-style accounting record on the annotations topic."""
        key, _text, label, conf = item[:4]
        cid = item[4] if len(item) == 5 else None
        rec = {"prediction": label, "label": label_name(label),
               "confidence": round(conf, 6), "analysis": None,
               "dropped": True, "reason": reason}
        if cid is not None:
            rec["trace"] = cid
        return json.dumps(rec).encode(), key, cid

    def _run(self) -> None:
        while True:
            with self._cv:
                while (not self._q and not self._drop_backlog
                       and not self._closed):
                    self._idle.set()
                    self._cv.wait(timeout=0.2)
                if not self._q and not self._drop_backlog and self._closed:
                    self._idle.set()
                    return
                drops, self._drop_backlog = self._drop_backlog, []
                batch = [self._q.popleft()
                         for _ in range(min(len(self._q), self.max_batch))]
            if drops:
                # Before the batch: a drop record must not wait behind a
                # multi-second decode — its row's accounting is already due.
                try:
                    self._emit_drops(drops)
                except Exception:  # noqa: BLE001 — lane must survive anything
                    # flightcheck: ignore[FC102] — worker-thread-only counter, read-racy by design (see __init__)
                    self.backend_errors += 1
                    log.exception("emitting %d drop records failed "
                                  "(counted in dropped, not drop_records)",
                                  len(drops))
            if not batch:
                continue
            try:
                self._annotate(batch)
            except Exception:  # noqa: BLE001 — lane must survive anything
                # flightcheck: ignore[FC102] — worker-thread-only counter, read-racy by design (see __init__)
                self.backend_errors += 1
                log.exception("annotation batch failed (%d rows dropped); "
                              "classification unaffected", len(batch))

    def _emit_drops(self, drops: List[tuple]) -> None:
        """Produce + flush the pending structured drop records (worker
        thread, the lane's own producer — same delivery accounting rule as
        annotation records: produce, then flush, then count delivered)."""
        for value, key, _cid in drops:
            self._producer.produce(self.topic, value, key=key)
        undelivered = int(self._producer.flush() or 0)
        delivered = len(drops) - min(len(drops), undelivered)
        # flightcheck: ignore[FC102] — worker-thread-only tally, read-racy by design
        self.drop_records += delivered
        if undelivered:
            log.warning("producer left %d drop records undelivered "
                        "(dropped counter stays ahead of drop_records)",
                        undelivered)
        if self._rowtrace is not None:
            for _value, _key, cid in drops:
                if cid is not None:
                    self._rowtrace.record_event(
                        cid, "annotate", ok=False,
                        detail="dropped:queue_overflow")

    def _annotate(self, batch: List[tuple]) -> None:
        # Items are (key, text, label, conf[, cid]) — the correlation id
        # rides only when the engine traces; normalize for both shapes.
        batch = [it if len(it) == 5 else (*it, None) for it in batch]
        keys, texts, labels, confs, cids = map(list, zip(*batch))
        tr = self._rowtrace
        t0 = time.perf_counter()
        try:
            if getattr(self._fn, "accepts_cids", False):
                # Slotserve hooks (explain/slotserve/make_slot_explain_hook)
                # take the rows' trace cids so each explanation's slot +
                # latency lands on the row's own chain(cid).
                analyses = self._fn(texts, labels, confs, cids=cids)
            else:
                analyses = self._fn(texts, labels, confs)
        except Exception as e:
            if tr is not None:
                # One failed explain span for the batch + a failed
                # annotate event per traced row: breaker fast-fails
                # (BreakerOpenError) land here too, so breaker-tripped
                # rows keep a complete chain by id.
                tr.record_span("lane", "explain",
                               time.perf_counter() - t0, ok=False,
                               detail=type(e).__name__)
                for cid in cids:
                    if cid is not None:
                        tr.record_event(cid, "annotate", ok=False,
                                        detail=type(e).__name__)
            raise
        if tr is not None:
            tr.record_span("lane", "explain", time.perf_counter() - t0,
                           detail=f"rows={len(batch)}")
        if len(analyses) != len(batch):  # mirrors the engine's inline check
            raise ValueError(f"explain_batch_fn returned {len(analyses)} "
                             f"analyses for {len(batch)} rows")
        out = []
        out_cids = []
        for key, label, conf, cid, analysis in zip(keys, labels, confs,
                                                   cids, analyses):
            if analysis is None:
                continue
            rec = {"prediction": label, "label": label_name(label),
                   "confidence": round(conf, 6), "analysis": analysis}
            out.append((json.dumps(rec).encode(), key))
            out_cids.append(cid)
        if out:
            batch_produce = getattr(self._producer, "produce_batch", None)
            if batch_produce is not None:
                batch_produce(self.topic, out)
            else:
                for value, key in out:
                    self._producer.produce(self.topic, value, key=key)
            # Flush before counting: with a real Kafka producer, produce()
            # only enqueues into librdkafka — records still queued when the
            # process exits are LOST, and the drop/annotated counters are
            # the lane's recorded-fact contract. Annotation batches take
            # seconds of decode, so a per-batch flush costs nothing.
            self.produced += len(out)
            undelivered = self._producer.flush()
            if undelivered:
                # flightcheck: ignore[FC102] — worker-thread-only counter, read-racy by design
                self.backend_errors += 1
                log.warning("producer left %d annotation records "
                            "undelivered (counted as not annotated)",
                            undelivered)
            # Running delivered tally: a later successful flush of records a
            # previous one left queued credits them then, exactly once. The
            # max() keeps the counter monotonic while the queue is deep.
            # flightcheck: ignore[FC102] — worker-thread-only tally, read-racy by design
            self.annotated = max(self.annotated,
                                 self.produced - int(undelivered))
            if self._rowtrace is not None:
                for cid in out_cids:
                    if cid is not None:
                        self._rowtrace.record_event(
                            cid, "annotate", ok=not undelivered)

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the queue is empty and the worker is idle (or
        timeout). The lane stays usable after. True = fully drained.

        Bounded even against a HUNG backend: a worker stuck inside
        ``explain_batch_fn`` never raises ``_idle``, so the wait simply
        expires — the caller gets False after ~``timeout``, never a
        deadlock. The deadline runs on the injectable ``clock``."""
        deadline = self._clock() + timeout
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                return False
            if self._idle.wait(timeout=min(remaining, 0.2)):
                with self._cv:
                    # Re-queued rows cleared _idle under the same lock (see
                    # submit), so observing idle + empty here is conclusive
                    # and a stale idle cannot busy-spin this loop. Pending
                    # drop records count as work: drained means every due
                    # accounting record reached the topic too.
                    if not self._q and not self._drop_backlog:
                        return True

    def close(self, timeout: float = 30.0) -> bool:
        """Drain best-effort, then stop the worker. True = clean shutdown
        (queue drained AND worker exited); False is honest about partial
        failure — rows discarded, or a worker hung in the backend (it is
        a daemon thread, so an un-joinable worker cannot block process
        exit, and a latched-closed lane drops any late submits).

        After the drain deadline the RESIDUAL QUEUE IS CLEARED under the
        lock, counting the discards as dropped, before ``_closed`` latches
        (ADVICE round 5): without this a slow worker kept draining
        multi-second LLM batches past close(), so ``annotation_stats()``
        read right after — serve.py's finish_annotations() does exactly
        that — snapshotted counters that were still mutating, and process
        exit could kill the daemon mid-flush. Clearing makes post-close
        stats quiescent up to the single batch already in the worker's
        hands (bounded by the join below).

        Never blocks unboundedly: the drain phase is capped by ``timeout``
        and the join by a short window scaled to it — a backend that
        ignores interruption costs the caller ~timeout, not forever."""
        drained = self.drain(timeout)
        with self._cv:
            residual = len(self._q)
            if residual:
                self.dropped += residual
                self._q.clear()
            self._closed = True
            self._cv.notify()
        self._thread.join(timeout=min(5.0, max(0.2, timeout)))
        alive = self._thread.is_alive()
        if alive:
            log.warning("annotation worker still running after close() "
                        "(hung backend?); daemon thread, counters may "
                        "move for one more batch")
        return drained and residual == 0 and not alive

    def stats(self) -> dict:
        with self._cv:
            depth = len(self._q)
            return {"submitted": self.submitted, "annotated": self.annotated,
                    "dropped": self.dropped,
                    "drop_records": self.drop_records,
                    "backend_errors": self.backend_errors,
                    "queue_depth": depth}
