"""Message transport interfaces + an in-process broker.

The reference's streaming transport is Kafka via confluent_kafka
(utils/kafka_utils.py: consumer with group id / earliest offsets / optional
SASL_SSL, producer, topics customer-dialogues-raw -> dialogues-classified).
This module defines the minimal consumer/producer protocol the serving engine
needs, with two implementations:

  * InProcessBroker — a partitioned, offset-tracked queue broker usable in
    tests and benchmarks with byte-identical message semantics (this is the
    injection seam the reference implicitly exposes at
    utils/kafka_utils.py:11,33 — SURVEY.md §4 point 3).
  * kafka.py — the real confluent_kafka client factories (same env vars as
    the reference), import-gated so the framework works without the wheel.

Semantics follow Kafka where it matters for the engine: per-partition FIFO,
consumer offsets advance only on commit (the reference never commits — Q2 —
and reprocesses from earliest on every restart; this engine commits after
produce, deliberately fixing that and documenting the difference), and
consumer-GROUP partition assignment: members of one group own disjoint
partition subsets (balanced-sticky assignor), rebalanced on join/leave/eviction,
with commits rejected for partitions the member no longer owns
(``CommitFailedError``, like Kafka on a stale generation). The reference
creates its topics with ``--partitions 3`` and a consumer group
(README; utils/kafka_utils.py:15) — N engines in one group scale out
horizontally exactly the way N reference consumers would.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Protocol, Sequence

from fraud_detection_tpu.utils.racecheck import ExclusiveRegion


@dataclass(slots=True)
class Message:
    """One broker record. Construction cost matters — the produce path
    builds one per message inside the engine's 50k+/sec hot loop — so the
    broker constructs these POSITIONALLY (~2x faster than kwargs; slotted
    dataclass also beats NamedTuple here)."""

    topic: str
    value: bytes
    key: Optional[bytes] = None
    partition: int = 0
    offset: int = -1
    timestamp: float = 0.0
    # Broker-global produce sequence. Timestamps are batch-shared (one
    # time.time() per append_batch), so they cannot order a batch's round-robin
    # messages across partitions; this can.
    seq: int = 0


class Consumer(Protocol):
    def poll(self, timeout: float = 1.0) -> Optional[Message]: ...
    def poll_batch(self, max_messages: int, timeout: float) -> List[Message]: ...
    def commit(self) -> None: ...

    def commit_offsets(self, offsets: Dict[tuple, int]) -> None:
        """Commit explicit next-offsets per (topic, partition). Unlike
        ``commit`` (which commits the consumer's current position), this lets
        a pipelined engine durably record batch N while batch N+1 is already
        consumed in flight."""
        ...

    def close(self) -> None: ...


class Producer(Protocol):
    def produce(self, topic: str, value: bytes, key: Optional[bytes] = None) -> None: ...

    def flush(self, timeout: float = 10.0) -> int:
        """Block until queued messages are delivered; returns how many are
        STILL undelivered (0 = fully drained, matching confluent_kafka)."""
        ...


class CommitFailedError(RuntimeError):
    """Commit advanced a partition this member does not currently own —
    the group rebalanced underneath it (Kafka's CommitFailedError). The
    engine treats this as a failed incarnation: offsets stay uncommitted and
    the partition's new owner reprocesses the batch (at-least-once)."""


class TransientBrokerError(RuntimeError):
    """Transport-level broker failure that is expected to heal (librdkafka's
    ``_TRANSPORT`` / ``_ALL_BROKERS_DOWN`` while retrying, or an injected
    chaos fault). Raised from the poll path; it kills the engine incarnation
    and the supervisor (``run_supervised``) restarts with backoff from the
    last committed offsets — unlike fatal client states, which should crash
    through. stream/kafka.py translates real librdkafka codes to this class
    so rebalance/outage survival behaves identically in tests (in-process
    broker + chaos wrappers) and production."""


class _GroupState:
    """Broker-side consumer-group bookkeeping (the group-coordinator role)."""

    __slots__ = ("generation", "members", "assignment", "acquired", "join_seq",
                 "next_evict_scan")

    def __init__(self):
        self.generation = 0
        self.members: Dict[str, dict] = {}      # member_id -> {topics, seen, joined}
        self.assignment: Dict[str, set] = {}    # member_id -> {(topic, partition)}
        self.next_evict_scan = 0.0              # liveness scans are rate-limited
        # (topic, partition) -> generation its CURRENT owner acquired it at.
        # This is what lets a consumer distinguish "I owned p continuously"
        # from "p bounced away and back while I wasn't polling" — the local
        # read-ahead position is only valid in the first case.
        self.acquired: Dict[tuple, int] = {}
        self.join_seq = itertools.count()


class InProcessBroker:
    """Thread-safe partitioned topic store with Kafka-ish offset semantics."""

    def __init__(self, num_partitions: int = 3, session_timeout: float = 300.0):
        self.num_partitions = num_partitions
        # Members that neither polled nor committed within this window are
        # evicted at the next group operation (zombie crash recovery). This
        # models Kafka's max.poll.interval.ms (default 300s) rather than its
        # heartbeat-thread session timeout: liveness here is poll/commit
        # activity, and a worker legitimately goes quiet for a whole
        # micro-batch of scoring + batched LLM explanations (tens of seconds
        # at bench rates). The supervised engine path closes consumers
        # explicitly, so eviction is the backstop, not the common path.
        self.session_timeout = session_timeout
        self._topics: Dict[str, List[List[Message]]] = {}
        # Group-durable committed offsets: (group, topic, partition) -> next
        # offset. Lives on the BROKER, like Kafka's __consumer_offsets — a
        # fresh consumer in the same group resumes where the group left off
        # (this is what makes crash/restart tests honest).
        self._group_offsets: Dict[tuple, int] = {}
        self._groups: Dict[str, _GroupState] = {}
        self._member_ids = itertools.count()
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._seq = itertools.count()

    def _partitions(self, topic: str) -> List[List[Message]]:
        with self._lock:
            if topic not in self._topics:
                self._topics[topic] = [[] for _ in range(self.num_partitions)]
            return self._topics[topic]

    def append(self, topic: str, value: bytes, key: Optional[bytes] = None) -> None:
        parts = self._partitions(topic)
        if key is not None:
            idx = hash(key) % len(parts)
        else:
            idx = next(self._rr) % len(parts)
        with self._lock:
            part = parts[idx]
            part.append(Message(topic, value, key, idx, len(part), time.time(),
                                next(self._seq)))

    def append_batch(self, topic: str,
                     items: Iterable[tuple]) -> None:
        """Append (value, key) pairs under ONE lock acquisition — the produce
        path runs per message at 30k+/sec, where per-message locking shows."""
        parts = self._partitions(topic)
        n_parts = len(parts)
        now = time.time()
        with self._lock:
            for value, key in items:
                idx = (hash(key) if key is not None else next(self._rr)) % n_parts
                part = parts[idx]
                part.append(Message(topic, value, key, idx, len(part), now,
                                    next(self._seq)))

    def topic_size(self, topic: str) -> int:
        parts = self._partitions(topic)
        with self._lock:
            return sum(len(p) for p in parts)

    def messages(self, topic: str) -> List[Message]:
        parts = self._partitions(topic)
        with self._lock:
            out = [m for p in parts for m in p]
        return sorted(out, key=lambda m: m.seq)

    def consumer(self, topics: Sequence[str], group_id: str = "default") -> "InProcessConsumer":
        return InProcessConsumer(self, list(topics), group_id)

    def assigned_consumer(self, partitions: Sequence[tuple],
                          group_id: str = "default", fence=None
                          ) -> "InProcessAssignedConsumer":
        """Manual-assignment consumer (Kafka's ``assign()`` mode): reads
        EXACTLY the given (topic, partition) pairs, never joins the group's
        assignor, commits into the same group-durable offsets. Partition
        exclusivity is the CALLER's contract — this is the transport the
        fleet coordinator's lease-based assignment drives
        (fraud_detection_tpu/fleet/, docs/fleet.md); ``fence`` lets that
        caller fail stale commits (see InProcessAssignedConsumer)."""
        return InProcessAssignedConsumer(self, list(partitions), group_id,
                                         fence=fence)

    def group_lag(self, group_id: str,
                  topics: Optional[Sequence[str]] = None) -> int:
        """Rows appended but not yet COMMITTED by ``group_id`` across
        ``topics`` (all topics when None). Unlike a consumer's ``backlog()``
        (unpolled rows behind one member's position), this counts from the
        group-durable offsets — so it still sees a dead member's polled-but-
        uncommitted rows, which is what makes it the fleet's drain-complete
        signal (fleet/coordinator.py ``committed_lag``)."""
        with self._lock:
            names = list(topics) if topics is not None else list(self._topics)
            total = 0
            for t in names:
                parts = self._topics.get(t)
                if parts is None:
                    continue
                for p, part in enumerate(parts):
                    total += max(0, len(part)
                                 - self._group_offsets.get((group_id, t, p), 0))
            return total

    def producer(self) -> "InProcessProducer":
        return InProcessProducer(self)

    # ------------------------------------------------------------------
    # group coordination (Kafka's group-coordinator role, in-process)
    # ------------------------------------------------------------------

    def _evict_expired_locked(self, group: _GroupState, now: float) -> bool:
        stale = [m for m, info in group.members.items()
                 if now - info["seen"] > self.session_timeout]
        for m in stale:
            del group.members[m]
        return bool(stale)

    def _rebalance_locked(self, group: _GroupState) -> None:
        """Balanced-sticky assignor (Kafka's sticky strategy): every member
        keeps the partitions it already owns up to its fair share; only
        orphaned partitions (owner left/evicted) and the excess above a
        shrunken share move. A pure round-robin re-deal shuffled partitions
        between UNINVOLVED survivors on every member exit, fencing their
        in-flight commits and forcing reprocessing (round-3 advisor finding
        on serve --workers). Bumps the generation — every member notices on
        its next poll and refreshes its owned set. Partitions that change
        hands get their acquisition generation restamped; continuously-owned
        ones keep it."""
        old_owner = {pair: m for m, pairs in group.assignment.items()
                     for pair in pairs}
        group.generation += 1
        members = sorted(group.members, key=lambda m: group.members[m]["joined"])
        group.assignment = {m: set() for m in members}
        topics = sorted({t for m in members for t in group.members[m]["topics"]})
        acquired: Dict[tuple, int] = {}
        for topic in topics:
            subs = [m for m in members if topic in group.members[m]["topics"]]
            pairs = [(topic, p) for p in range(self.num_partitions)]
            base, extra = divmod(len(pairs), len(subs))
            target = {m: base + (1 if i < extra else 0)
                      for i, m in enumerate(subs)}
            kept: Dict[str, list] = {m: [] for m in subs}
            pool = []
            for pair in pairs:           # partition order -> deterministic
                m = old_owner.get(pair)
                if m in target and len(kept[m]) < target[m]:
                    kept[m].append(pair)
                else:
                    pool.append(pair)
            for m in subs:               # join order -> deterministic
                take = target[m] - len(kept[m])
                if take > 0:
                    kept[m].extend(pool[:take])
                    del pool[:take]
            for m in subs:
                for pair in kept[m]:
                    group.assignment[m].add(pair)
                    acquired[pair] = (group.acquired.get(pair, group.generation)
                                      if old_owner.get(pair) == m
                                      else group.generation)
        group.acquired = acquired

    def _join_group(self, group_id: str, topics: Sequence[str]) -> str:
        with self._lock:
            group = self._groups.setdefault(group_id, _GroupState())
            now = time.monotonic()  # liveness: immune to wall-clock steps
            self._evict_expired_locked(group, now)
            member_id = f"{group_id}-{next(self._member_ids)}"
            group.members[member_id] = {"topics": tuple(topics), "seen": now,
                                        "joined": next(group.join_seq)}
            self._rebalance_locked(group)
            return member_id

    def _leave_group(self, group_id: str, member_id: str) -> None:
        with self._lock:
            group = self._groups.get(group_id)
            if group is None or member_id not in group.members:
                return
            del group.members[member_id]
            self._rebalance_locked(group)

    def _sync_member_locked(self, group_id: str, member_id: str,
                            topics: Sequence[str],
                            known_generation: int = -1) -> tuple:
        """Heartbeat + assignment fetch (caller holds self._lock). Returns
        (generation, owned set, {pair: acquisition generation}) — or
        (known_generation, None, None) on the fast path: member known,
        generation unchanged, no liveness scan due. poll()'s 1 ms spin calls
        this ~1000x/sec per idle consumer, so the common case must be a
        heartbeat write and two compares, not an O(members) scan plus a dict
        build that _refresh_locked would throw away. Eviction scans are
        rate-limited to session_timeout/4, which bounds zombie-stall at
        ~1.25x the configured timeout. An evicted member transparently
        rejoins — Kafka's rejoin-after-session-expiry, minus the error
        round trip."""
        group = self._groups.setdefault(group_id, _GroupState())
        now = time.monotonic()  # liveness: immune to wall-clock steps
        member = group.members.get(member_id)
        if member is not None:
            member["seen"] = now
            if group.generation == known_generation and now < group.next_evict_scan:
                return known_generation, None, None
        changed = False
        if now >= group.next_evict_scan:
            changed = self._evict_expired_locked(group, now)
            group.next_evict_scan = now + self.session_timeout / 4
        if member_id not in group.members:
            group.members[member_id] = {"topics": tuple(topics), "seen": now,
                                        "joined": next(group.join_seq)}
            changed = True
        if changed:
            self._rebalance_locked(group)
        if group.generation == known_generation:
            return known_generation, None, None
        owned = group.assignment[member_id]
        return (group.generation, owned,
                {pair: group.acquired[pair] for pair in owned})

    def group_assignment(self, group_id: str) -> Dict[str, List[tuple]]:
        """Current member -> sorted[(topic, partition)] map (observability)."""
        with self._lock:
            group = self._groups.get(group_id)
            if group is None:
                return {}
            return {m: sorted(pairs) for m, pairs in group.assignment.items()}


class InProcessConsumer:
    """Earliest-offset consumer with manual commit (auto-commit off, like the
    reference's config — utils/kafka_utils.py:16-17)."""

    def __init__(self, broker: InProcessBroker, topics: List[str], group_id: str):
        self.broker = broker
        self.topics = topics
        self.group_id = group_id
        self.member_id = broker._join_group(group_id, topics)
        # Stale until the first poll refreshes it against the coordinator.
        self._generation = -1
        self._owned: set = set()
        self._acquired: Dict[tuple, int] = {}
        self._position: Dict[tuple, int] = {}
        self._committed: Dict[tuple, int] = {}
        self._closed = False
        # Kafka consumers are not thread-safe and neither is this one
        # (._position/._committed are read-modify-write). The region turns
        # concurrent poll/commit from two threads into a RaceError instead of
        # lost offsets (utils/racecheck.py).
        self._region = ExclusiveRegion("InProcessConsumer")

    def _refresh_locked(self) -> None:
        """Heartbeat + adopt the current assignment (caller holds broker lock).
        On a generation change: partitions owned CONTINUOUSLY (same
        acquisition generation on both sides) keep their local read-ahead
        position; everything else — newly gained, or bounced away-and-back
        while this member wasn't polling (eviction/rejoin, an intervening
        member's whole tenure) — resumes from the GROUP's committed offsets
        (auto.offset.reset='earliest' applies only where the group never
        committed). Dropped partitions forget their local positions — their
        new owner is authoritative now.

        Raises on a closed consumer: Kafka errors on use-after-close, and the
        transparent-rejoin path would otherwise re-register the member and
        strand its partitions until the session timeout (the read is ordered
        by the broker lock against close(), which sets the flag before
        leaving the group)."""
        if self._closed:
            raise RuntimeError(
                f"consumer {self.member_id!r} (group {self.group_id!r}) is closed")
        gen, owned, acquired = self.broker._sync_member_locked(
            self.group_id, self.member_id, self.topics, self._generation)
        if owned is None:
            return
        offsets = self.broker._group_offsets
        self._position = {
            key: (self._position.get(key, offsets.get((self.group_id, *key), 0))
                  if self._acquired.get(key) == acquired[key]
                  else offsets.get((self.group_id, *key), 0))
            for key in owned}
        self._acquired = dict(acquired)
        # Seed _committed to the group watermark wherever the position was
        # seeded from it: "uncommitted read-ahead" must mean LOCAL
        # consumption beyond the committed point — without the seed, a
        # group-resumed position on a never-read partition looked like
        # read-ahead and commit() raised spuriously after losing it
        # (fifth-pass review repro).
        self._committed = {
            key: max(self._committed.get(key, 0),
                     offsets.get((self.group_id, *key), 0))
            for key in owned}
        self._owned = set(owned)
        self._generation = gen

    def assignment(self) -> List[tuple]:
        """This member's current (topic, partition) ownership (refreshed)."""
        with self._region, self.broker._lock:
            self._refresh_locked()
            return sorted(self._owned)

    def _next_from(self, topic: str, part_idx: int) -> Optional[Message]:
        parts = self.broker._partitions(topic)
        key = (topic, part_idx)
        pos = self._position.get(key, 0)
        with self.broker._lock:
            part = parts[part_idx]
            if pos < len(part):
                self._position[key] = pos + 1
                return part[pos]
        return None

    def poll(self, timeout: float = 1.0) -> Optional[Message]:
        with self._region:
            deadline = time.time() + timeout
            while True:
                with self.broker._lock:
                    self._refresh_locked()
                for topic, p in sorted(self._owned):
                    msg = self._next_from(topic, p)
                    if msg is not None:
                        return msg
                if time.time() >= deadline:
                    return None
                time.sleep(0.001)

    def poll_batch(self, max_messages: int, timeout: float) -> List[Message]:
        """Drain up to max_messages; waits at most ``timeout`` for the first.

        After the (possibly waiting) first message, the rest of the batch is
        sliced per owned partition under one lock — not polled one message at
        a time (per-message lock traffic was ~15% of the serve loop's host
        budget at 35k msgs/sec)."""
        out: List[Message] = []
        first = self.poll(timeout)
        if first is None:
            return out
        out.append(first)
        with self._region, self.broker._lock:
            for topic, p_idx in sorted(self._owned):
                if len(out) >= max_messages:
                    return out
                all_parts = self.broker._topics.get(topic)
                if all_parts is None:
                    continue
                part = all_parts[p_idx]
                key = (topic, p_idx)
                pos = self._position.get(key, 0)
                take = min(len(part) - pos, max_messages - len(out))
                if take > 0:
                    out.extend(part[pos : pos + take])
                    self._position[key] = pos + take
        return out

    def commit(self) -> None:
        with self._region:
            # Refresh first: a rebalance prunes _position to owned partitions,
            # so this never advances group offsets for a partition whose new
            # owner is already authoritative.
            # BOTH maps must be snapshotted before the refresh: it prunes
            # lost partitions from _committed too, so comparing post-refresh
            # would read an already-committed watermark as 0 and raise
            # spuriously for fully-committed read-ahead (fourth-pass review
            # repro; commit_offsets always had the pre-refresh snapshot).
            before_pos = dict(self._position)
            before_committed = dict(self._committed)
            before_acq = dict(self._acquired)
            with self.broker._lock:
                self._refresh_locked()
            # Kafka parity with the adapter (round-3 full-round review): a
            # commit whose UNCOMMITTED read-ahead was fenced away raises the
            # same CommitFailedError real Kafka's commit() surfaces — silent
            # success here while production raises is the test/prod
            # divergence the error translation exists to eliminate. A
            # partition that bounced away AND BACK between polls is owned
            # again but restamped (new acquisition generation, position
            # reset to the group watermark): its old tenure's read-ahead is
            # equally gone, and real Kafka raises on the stale generation —
            # so restamped keys fence exactly like lost ones (round-3
            # advisor finding).
            lost = sorted(key for key, pos in before_pos.items()
                          if (key not in self._owned
                              or self._acquired.get(key) != before_acq.get(key))
                          and pos > before_committed.get(key, 0))
            if lost:
                raise CommitFailedError(
                    f"group {self.group_id!r} rebalanced: member "
                    f"{self.member_id!r} no longer owns {lost}; "
                    "offsets stay uncommitted — the new owner reprocesses")
            self._committed.update(self._position)
            self._write_through()

    def commit_offsets(self, offsets: Dict[tuple, int]) -> None:
        with self._region:
            advances = {key: off for key, off in offsets.items()
                        if off > self._committed.get(key, 0)}
            with self.broker._lock:
                self._refresh_locked()
                lost = [key for key in advances if key not in self._owned]
                if lost:
                    raise CommitFailedError(
                        f"group {self.group_id!r} rebalanced: member "
                        f"{self.member_id!r} no longer owns {sorted(lost)}; "
                        "offsets stay uncommitted — the new owner reprocesses")
            self._committed.update(advances)
            self._write_through()

    def _write_through(self) -> None:
        with self.broker._lock:
            for (t, p), off in self._committed.items():
                key = (self.group_id, t, p)
                if off > self.broker._group_offsets.get(key, 0):
                    self.broker._group_offsets[key] = off

    def committed_offsets(self) -> Dict[tuple, int]:
        return dict(self._committed)

    def backlog(self) -> int:
        """Rows appended to this member's owned partitions but not yet
        polled — the queue-depth signal the scheduler's admission watermark
        reads (sched/admission.py). Engine-thread only (same single-driver
        contract as poll/commit; the region enforces it)."""
        with self._region, self.broker._lock:
            self._refresh_locked()
            total = 0
            for topic, p in self._owned:
                parts = self.broker._topics.get(topic)
                if parts is not None:
                    total += max(0, len(parts[p])
                                 - self._position.get((topic, p), 0))
            return total

    def seek_to_committed(self) -> None:
        """Simulate a restart: resume every owned partition from the GROUP's
        durable offsets. (Local ``_committed`` can never exceed these:
        ``_write_through`` pushes each commit to the broker immediately and
        fencing stops other members advancing an owned partition — so the
        group map IS the committed truth, including for a fresh consumer
        that committed nothing this session, which the old
        ``dict(_committed)`` rewound to 0.)"""
        with self._region, self.broker._lock:
            self._refresh_locked()
            offsets = self.broker._group_offsets
            self._position = {key: offsets.get((self.group_id, *key), 0)
                              for key in self._owned}

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self.broker._leave_group(self.group_id, self.member_id)


class InProcessAssignedConsumer:
    """Manual-assignment consumer: an explicit (topic, partition) set, no
    group membership, commits write through to the group-durable offsets.

    Kafka's ``assign()`` mode: ownership/exclusivity lives OUTSIDE the
    broker — here, in the fleet coordinator's partition leases (fleet/
    coordinator.py). Construction resumes every pair from the group's
    committed offsets (earliest where the group never committed), which is
    the zero-loss handoff contract: whatever a dead owner failed to commit
    is exactly what the next owner re-reads. An optional ``fence`` callable
    is consulted at commit time so a revoked lease turns a stale commit
    into ``CommitFailedError`` instead of silently advancing a partition
    someone else now owns (the in-process analogue of Kafka's stale-
    generation fencing for group commits)."""

    def __init__(self, broker: InProcessBroker, partitions: Sequence[tuple],
                 group_id: str, fence=None):
        self.broker = broker
        self.group_id = group_id
        self.partitions = [tuple(p) for p in partitions]
        self._fence = fence
        self._closed = False
        with broker._lock:
            offsets = broker._group_offsets
            self._position: Dict[tuple, int] = {
                pair: offsets.get((group_id, *pair), 0)
                for pair in self.partitions}
        self._committed: Dict[tuple, int] = dict(self._position)
        # Same single-driver contract as InProcessConsumer: poll/commit are
        # read-modify-write on the position maps.
        self._region = ExclusiveRegion("InProcessAssignedConsumer")

    def _check_open(self) -> None:
        if self._closed:
            raise RuntimeError(
                f"assigned consumer (group {self.group_id!r}, "
                f"{self.partitions}) is closed")

    def assignment(self) -> List[tuple]:
        return sorted(self.partitions)

    def poll(self, timeout: float = 1.0) -> Optional[Message]:
        with self._region:
            self._check_open()
            deadline = time.time() + timeout
            while True:
                for topic, p in sorted(self.partitions):
                    parts = self.broker._partitions(topic)
                    key = (topic, p)
                    pos = self._position.get(key, 0)
                    with self.broker._lock:
                        part = parts[p]
                        if pos < len(part):
                            self._position[key] = pos + 1
                            return part[pos]
                if time.time() >= deadline:
                    return None
                time.sleep(0.001)

    def poll_batch(self, max_messages: int, timeout: float) -> List[Message]:
        out: List[Message] = []
        first = self.poll(timeout)
        if first is None:
            return out
        out.append(first)
        with self._region, self.broker._lock:
            for topic, p in sorted(self.partitions):
                if len(out) >= max_messages:
                    return out
                all_parts = self.broker._topics.get(topic)
                if all_parts is None:
                    continue
                part = all_parts[p]
                key = (topic, p)
                pos = self._position.get(key, 0)
                take = min(len(part) - pos, max_messages - len(out))
                if take > 0:
                    out.extend(part[pos : pos + take])
                    self._position[key] = pos + take
        return out

    def commit(self) -> None:
        with self._region:
            self._check_open()
            self._commit_locked(dict(self._position))

    def commit_offsets(self, offsets: Dict[tuple, int]) -> None:
        with self._region:
            self._check_open()
            self._commit_locked({key: off for key, off in offsets.items()
                                 if off > self._committed.get(key, 0)})

    def _commit_locked(self, advances: Dict[tuple, int]) -> None:
        fence = self._fence
        if fence is not None and advances:
            lost = fence(sorted(advances))
            if lost:
                raise CommitFailedError(
                    f"lease for {sorted(lost)} was revoked from this worker "
                    f"(group {self.group_id!r}); offsets stay uncommitted — "
                    "the partitions' new owner reprocesses")
        self._committed.update(advances)
        with self.broker._lock:
            for (t, p), off in advances.items():
                key = (self.group_id, t, p)
                if off > self.broker._group_offsets.get(key, 0):
                    self.broker._group_offsets[key] = off

    def committed_offsets(self) -> Dict[tuple, int]:
        return dict(self._committed)

    def backlog(self) -> int:
        """Rows appended to the assigned partitions but not yet polled (the
        scheduler's local queue-depth signal; the fleet coordinator
        aggregates these into the GLOBAL watermark)."""
        with self._region, self.broker._lock:
            total = 0
            for topic, p in self.partitions:
                parts = self.broker._topics.get(topic)
                if parts is not None:
                    total += max(0, len(parts[p])
                                 - self._position.get((topic, p), 0))
            return total

    def close(self) -> None:
        self._closed = True   # no group to leave: assignment is external


class InProcessProducer:
    def __init__(self, broker: InProcessBroker):
        self.broker = broker
        self._pending = 0

    def produce(self, topic: str, value: bytes, key: Optional[bytes] = None) -> None:
        self.broker.append(topic, value, key)

    def produce_batch(self, topic: str, items: Iterable[tuple]) -> None:
        """Produce (value, key) pairs in one call (single lock acquisition)."""
        self.broker.append_batch(topic, items)

    def flush(self, timeout: float = 10.0) -> int:
        return 0  # in-process appends are synchronous; nothing can be pending
