"""Message transport interfaces + an in-process broker.

The reference's streaming transport is Kafka via confluent_kafka
(utils/kafka_utils.py: consumer with group id / earliest offsets / optional
SASL_SSL, producer, topics customer-dialogues-raw -> dialogues-classified).
This module defines the minimal consumer/producer protocol the serving engine
needs, with two implementations:

  * InProcessBroker — a partitioned, offset-tracked queue broker usable in
    tests and benchmarks with byte-identical message semantics (this is the
    injection seam the reference implicitly exposes at
    utils/kafka_utils.py:11,33 — SURVEY.md §4 point 3).
  * kafka.py — the real confluent_kafka client factories (same env vars as
    the reference), import-gated so the framework works without the wheel.

Semantics follow Kafka where it matters for the engine: per-partition FIFO,
consumer offsets advance only on commit (the reference never commits — Q2 —
and reprocesses from earliest on every restart; this engine commits after
produce, deliberately fixing that and documenting the difference).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Protocol, Sequence

from fraud_detection_tpu.utils.racecheck import ExclusiveRegion


@dataclass(slots=True)
class Message:
    """One broker record. Construction cost matters — the produce path
    builds one per message inside the engine's 50k+/sec hot loop — so the
    broker constructs these POSITIONALLY (~2x faster than kwargs; slotted
    dataclass also beats NamedTuple here)."""

    topic: str
    value: bytes
    key: Optional[bytes] = None
    partition: int = 0
    offset: int = -1
    timestamp: float = 0.0
    # Broker-global produce sequence. Timestamps are batch-shared (one
    # time.time() per append_batch), so they cannot order a batch's round-robin
    # messages across partitions; this can.
    seq: int = 0


class Consumer(Protocol):
    def poll(self, timeout: float = 1.0) -> Optional[Message]: ...
    def poll_batch(self, max_messages: int, timeout: float) -> List[Message]: ...
    def commit(self) -> None: ...

    def commit_offsets(self, offsets: Dict[tuple, int]) -> None:
        """Commit explicit next-offsets per (topic, partition). Unlike
        ``commit`` (which commits the consumer's current position), this lets
        a pipelined engine durably record batch N while batch N+1 is already
        consumed in flight."""
        ...

    def close(self) -> None: ...


class Producer(Protocol):
    def produce(self, topic: str, value: bytes, key: Optional[bytes] = None) -> None: ...

    def flush(self, timeout: float = 10.0) -> int:
        """Block until queued messages are delivered; returns how many are
        STILL undelivered (0 = fully drained, matching confluent_kafka)."""
        ...


class InProcessBroker:
    """Thread-safe partitioned topic store with Kafka-ish offset semantics."""

    def __init__(self, num_partitions: int = 3):
        self.num_partitions = num_partitions
        self._topics: Dict[str, List[List[Message]]] = {}
        # Group-durable committed offsets: (group, topic, partition) -> next
        # offset. Lives on the BROKER, like Kafka's __consumer_offsets — a
        # fresh consumer in the same group resumes where the group left off
        # (this is what makes crash/restart tests honest).
        self._group_offsets: Dict[tuple, int] = {}
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._seq = itertools.count()

    def _partitions(self, topic: str) -> List[List[Message]]:
        with self._lock:
            if topic not in self._topics:
                self._topics[topic] = [[] for _ in range(self.num_partitions)]
            return self._topics[topic]

    def append(self, topic: str, value: bytes, key: Optional[bytes] = None) -> None:
        parts = self._partitions(topic)
        if key is not None:
            idx = hash(key) % len(parts)
        else:
            idx = next(self._rr) % len(parts)
        with self._lock:
            part = parts[idx]
            part.append(Message(topic, value, key, idx, len(part), time.time(),
                                next(self._seq)))

    def append_batch(self, topic: str,
                     items: Iterable[tuple]) -> None:
        """Append (value, key) pairs under ONE lock acquisition — the produce
        path runs per message at 30k+/sec, where per-message locking shows."""
        parts = self._partitions(topic)
        n_parts = len(parts)
        now = time.time()
        with self._lock:
            for value, key in items:
                idx = (hash(key) if key is not None else next(self._rr)) % n_parts
                part = parts[idx]
                part.append(Message(topic, value, key, idx, len(part), now,
                                    next(self._seq)))

    def topic_size(self, topic: str) -> int:
        parts = self._partitions(topic)
        with self._lock:
            return sum(len(p) for p in parts)

    def messages(self, topic: str) -> List[Message]:
        parts = self._partitions(topic)
        with self._lock:
            out = [m for p in parts for m in p]
        return sorted(out, key=lambda m: m.seq)

    def consumer(self, topics: Sequence[str], group_id: str = "default") -> "InProcessConsumer":
        return InProcessConsumer(self, list(topics), group_id)

    def producer(self) -> "InProcessProducer":
        return InProcessProducer(self)


class InProcessConsumer:
    """Earliest-offset consumer with manual commit (auto-commit off, like the
    reference's config — utils/kafka_utils.py:16-17)."""

    def __init__(self, broker: InProcessBroker, topics: List[str], group_id: str):
        self.broker = broker
        self.topics = topics
        self.group_id = group_id
        # Start from the group's broker-durable committed offsets (Kafka
        # semantics: auto.offset.reset='earliest' applies only to partitions
        # the group has never committed).
        with broker._lock:
            self._position: Dict[tuple, int] = {
                (t, p): off for (g, t, p), off in broker._group_offsets.items()
                if g == group_id and t in topics}
        self._committed: Dict[tuple, int] = dict(self._position)
        self._closed = False
        # Kafka consumers are not thread-safe and neither is this one
        # (._position/._committed are read-modify-write). The region turns
        # concurrent poll/commit from two threads into a RaceError instead of
        # lost offsets (utils/racecheck.py).
        self._region = ExclusiveRegion("InProcessConsumer")

    def _next_from(self, topic: str, part_idx: int) -> Optional[Message]:
        parts = self.broker._partitions(topic)
        key = (topic, part_idx)
        pos = self._position.get(key, 0)
        with self.broker._lock:
            part = parts[part_idx]
            if pos < len(part):
                self._position[key] = pos + 1
                return part[pos]
        return None

    def poll(self, timeout: float = 1.0) -> Optional[Message]:
        with self._region:
            deadline = time.time() + timeout
            while True:
                for topic in self.topics:
                    for p in range(self.broker.num_partitions):
                        msg = self._next_from(topic, p)
                        if msg is not None:
                            return msg
                if time.time() >= deadline:
                    return None
                time.sleep(0.001)

    def poll_batch(self, max_messages: int, timeout: float) -> List[Message]:
        """Drain up to max_messages; waits at most ``timeout`` for the first.

        After the (possibly waiting) first message, the rest of the batch is
        sliced per partition under one lock — not polled one message at a
        time (per-message lock traffic was ~15% of the serve loop's host
        budget at 35k msgs/sec)."""
        out: List[Message] = []
        first = self.poll(timeout)
        if first is None:
            return out
        out.append(first)
        with self._region, self.broker._lock:
            for topic in self.topics:
                all_parts = self.broker._topics.get(topic)
                if all_parts is None:
                    continue
                for p_idx, part in enumerate(all_parts):
                    if len(out) >= max_messages:
                        return out
                    key = (topic, p_idx)
                    pos = self._position.get(key, 0)
                    take = min(len(part) - pos, max_messages - len(out))
                    if take > 0:
                        out.extend(part[pos : pos + take])
                        self._position[key] = pos + take
        return out

    def commit(self) -> None:
        with self._region:
            self._committed.update(self._position)
            self._write_through()

    def commit_offsets(self, offsets: Dict[tuple, int]) -> None:
        with self._region:
            for key, off in offsets.items():
                if off > self._committed.get(key, 0):
                    self._committed[key] = off
            self._write_through()

    def _write_through(self) -> None:
        with self.broker._lock:
            for (t, p), off in self._committed.items():
                key = (self.group_id, t, p)
                if off > self.broker._group_offsets.get(key, 0):
                    self.broker._group_offsets[key] = off

    def committed_offsets(self) -> Dict[tuple, int]:
        return dict(self._committed)

    def seek_to_committed(self) -> None:
        """Simulate a restart: resume from the last committed offsets."""
        self._position = dict(self._committed)

    def close(self) -> None:
        self._closed = True


class InProcessProducer:
    def __init__(self, broker: InProcessBroker):
        self.broker = broker
        self._pending = 0

    def produce(self, topic: str, value: bytes, key: Optional[bytes] = None) -> None:
        self.broker.append(topic, value, key)

    def produce_batch(self, topic: str, items: Iterable[tuple]) -> None:
        """Produce (value, key) pairs in one call (single lock acquisition)."""
        self.broker.append_batch(topic, items)

    def flush(self, timeout: float = 10.0) -> int:
        return 0  # in-process appends are synchronous; nothing can be pending
